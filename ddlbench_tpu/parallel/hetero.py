"""Uneven per-stage replication — the reference's hybrid PP×DP plans executed
TPU-natively.

Reference mechanism: the hierarchical optimizer emits per-stage replication
factors (pipedream-fork/optimizer/optimizer_graph_hierarchical.py:103-191);
run_template.sh parses its stdout into a ``stage:replication`` map
(run/run/run_template.sh:436-498); the runtime round-robins minibatches over a
stage's replica ranks, fixing the per-rank iteration counts by LCM when the
factors are uneven (pipedream-fork/runtime/runtime.py:663-690).

TPU-native design. Regular 2-D ('data','stage') meshes cannot host unequal
replica counts, so the whole pipeline lives on ONE flat mesh axis:

* axis 'pipe' of N = sum(r_s) devices; device d statically owns
  (stage_of[d], rep_of[d]); replicas of a stage occupy a contiguous range.
* Replication = intra-stage batch splitting: EVERY microbatch passes through
  every stage, replica k of stage s computing rows
  [k·mb/r_s, (k+1)·mb/r_s). Synchronous-pipeline updates are then exactly the
  uniform pipeline's updates (mod float reduction order) — a stronger
  equivalence than the reference's whole-minibatch round-robin, which changes
  per-replica batch statistics.
* Boundary transfer = a conveyor: R rounds of ONE right-shift ppermute chain
  (d -> d+1). At round 0 every device injects its row-shard (scattered into a
  full-microbatch buffer); every later round it forwards what it received.
  The payload a device receives at round t originated at device d-t, so a
  static (device, round) accept table adds exactly the payloads coming from
  its input boundary's producers. R = max_b (r_b + r_{b+1} - 1). jax.grad
  transposes the conveyor into the reversed (left-shift) schedule for free.
* Per-stage gradient sync / BN-state sync = subgroup ring allreduce over each
  stage's contiguous replica range (carry/total scheme, add-rounds gated by
  the group size so small groups stop before recycling).
* Token models compose: the last-stage branch runs the fused projection+loss
  (ops/fused_xent.py via parallel/common.fused_slice_* — no [rows, V] logits
  materialized) when cfg.fused_head_loss and the model's head supports it,
  exactly like the uniform pipelines. MoE aux losses are averaged over a
  stage's replica group (each replica sees 1/r of the rows), so the 'pipe'
  psum recovers the per-stage mean instead of r-times it.
"""

from __future__ import annotations

import math
from typing import Any, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_slice, init_model
from ddlbench_tpu.parallel.common import (
    cast_input, cast_params, correct_and_count, correct_topk,
    cross_entropy_loss, fused_slice_eval_sums, fused_slice_loss_sums,
    head_fusable, make_optimizer, vary as _vary_axes)
from ddlbench_tpu.parallel.gpipe import _shard_map
from ddlbench_tpu.parallel.packing import (
    balanced_stage_bounds, layer_flop_costs, pack_stages, pad_vec)


def _vary(v):
    return _vary_axes(v, ("pipe",))


class HeteroTrainState(NamedTuple):
    params: jax.Array  # [N, L] f32; row d = stage_of[d]'s packed params
    model_state: jax.Array  # [N, Ls]
    opt: Any  # optimizer dict pytree, leaves [N, X]


def _plan_tables(repl: Sequence[int]):
    """Static topology tables for a replication plan.

    Returns (stage_of[N], rep_of[N], offsets[S+1], accept[N][R] bool, R).
    accept[d][t] is True when the conveyor payload arriving at device d on
    round t originated from a producer of d's input boundary (device d-t-1
    after t+1 shifts... the chain shifts once per round, so round t delivers
    the round-0 injection of device d-(t+1)).
    """
    S = len(repl)
    offsets = [0]
    for r in repl:
        offsets.append(offsets[-1] + r)
    N = offsets[-1]
    stage_of = np.zeros(N, np.int32)
    rep_of = np.zeros(N, np.int32)
    for s in range(S):
        for k in range(repl[s]):
            d = offsets[s] + k
            stage_of[d] = s
            rep_of[d] = k
    R = 0
    for s in range(S - 1):
        R = max(R, repl[s] + repl[s + 1] - 1)
    accept = np.zeros((N, max(R, 1)), bool)
    for d in range(N):
        s = stage_of[d]
        if s == 0:
            continue
        lo, hi = offsets[s - 1], offsets[s - 1] + repl[s - 1]
        for t in range(R):
            origin = d - (t + 1)
            if lo <= origin < hi:
                accept[d, t] = True
    return stage_of, rep_of, offsets, accept, R


class HeteroGPipeStrategy:
    """strategy='gpipe' with uneven ``stage_replication`` — synchronous
    micro-batch pipeline over the flat 'pipe' mesh axis."""

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 devices: Optional[Sequence[jax.Device]] = None,
                 stage_bounds: Optional[List[int]] = None,
                 replication: Optional[Sequence[int]] = None):
        self.model = model
        self.cfg = cfg
        repl = tuple(replication or cfg.stage_replication or ())
        if not repl:
            raise ValueError("HeteroGPipeStrategy needs stage_replication")
        self.repl = repl
        self.num_stages = len(repl)
        self.N = sum(repl)
        if cfg.num_devices != self.N:
            raise ValueError(
                f"stage_replication {repl} sums to {self.N} but "
                f"num_devices={cfg.num_devices}")
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self.mb, self.num_microbatches = cfg.resolved_batches()
        for s, r in enumerate(repl):
            if self.mb % r:
                raise ValueError(
                    f"micro-batch {self.mb} not divisible by stage {s}'s "
                    f"replication {r}")
        from ddlbench_tpu.distributed import make_mesh

        self.mesh = make_mesh([("pipe", self.N)], devices=devices)
        self._fused = bool(cfg.fused_head_loss) and head_fusable(model)
        (self._stage_of, self._rep_of, self._offsets, self._accept,
         self._R) = _plan_tables(repl)
        self._stage_bounds_override = stage_bounds
        self._opt_init, self._opt_update = make_optimizer(cfg)
        self._built = False

    # -- initialization ----------------------------------------------------

    def init(self, key) -> HeteroTrainState:
        params_list, state_list, shapes = init_model(self.model, key)
        S = self.num_stages
        bounds = getattr(self, "bounds", None)
        if bounds is None:
            if self._stage_bounds_override is not None:
                bounds = list(self._stage_bounds_override)
            else:
                costs = layer_flop_costs(params_list, shapes,
                                          self.model.layers)
                bounds = balanced_stage_bounds(costs, S)
            assert (len(bounds) == S + 1 and bounds[0] == 0
                    and bounds[-1] == len(self.model.layers))
            self.bounds = bounds
            self.shapes = shapes

        params_mat, p_unravels, p_lens = pack_stages(
            [params_list[bounds[s]:bounds[s + 1]] for s in range(S)])
        state_mat, s_unravels, s_lens = pack_stages(
            [state_list[bounds[s]:bounds[s + 1]] for s in range(S)])
        # expand stage rows to device rows (replicas share their stage's row)
        params_mat = jnp.take(params_mat, jnp.asarray(self._stage_of), axis=0)
        state_mat = jnp.take(state_mat, jnp.asarray(self._stage_of), axis=0)

        if not self._built:
            self._p_unravels, self._p_lens = p_unravels, p_lens
            self._s_unravels, self._s_lens = s_unravels, s_lens
            interior = [
                self.mb * math.prod(self.shapes[bounds[s]])
                for s in range(1, S)
            ]
            self._act_size = max(interior) if interior else 1
            self._build_steps()

        from ddlbench_tpu.distributed import put_global_batch

        sh = self._row_sharding
        params_mat = put_global_batch(params_mat, sh)
        state_mat = put_global_batch(state_mat, sh)
        opt = self._opt_init(params_mat, step_like=(self.N, 1))
        if "step" in opt:
            opt = {**opt, "step": put_global_batch(opt["step"], sh)}
        return HeteroTrainState(params_mat, state_mat, opt)

    # -- branches ----------------------------------------------------------

    def _make_branch(self, s: int, train: bool):
        """Stage-s branch for lax.switch. Signature (shared by all stages):
        (param_row, state_row, in_total, xs, ys, m, rep) ->
        (contrib[A], new_state_row, obj_sum, ce_sum, aux_sum, correct,
         correct5, valid_count)
        where all loss outputs are SUMS over this device's row-shard (zeros
        off the last stage) and ``contrib`` is the device's rows of the
        output activation scattered into a zeroed full-microbatch buffer.
        """
        S, mb, A = self.num_stages, self.mb, self._act_size
        layers = self.model.layers[self.bounds[s]:self.bounds[s + 1]]
        in_shape = self.shapes[self.bounds[s]]
        p_unravel, p_len = self._p_unravels[s], self._p_lens[s]
        s_unravel, s_len = self._s_unravels[s], self._s_lens[s]
        cdtype = self.compute_dtype
        r = self.repl[s]
        rows = mb // r
        in_elem = math.prod(in_shape)
        last = s == S - 1
        fused = last and self._fused
        if not last:
            out_shape = self.shapes[self.bounds[s + 1]]
            out_elem = math.prod(out_shape)
        smooth = self.cfg.resolved_label_smoothing() if train else 0.0
        from ddlbench_tpu.models.moe import collect_aux_losses

        def branch(param_row, state_row, in_total, xs, ys, m, rep):
            if s == 0:
                # xs is already this device's row shard (shard_batch)
                x = lax.dynamic_index_in_dim(xs, m, keepdims=False)
            else:
                flat = lax.dynamic_slice(
                    in_total, (rep * rows * in_elem,), (rows * in_elem,))
                x = flat.reshape(rows, *in_shape)
            params = cast_params(p_unravel(param_row[:p_len]), cdtype)
            states = s_unravel(state_row[:s_len])
            zero_f = jnp.zeros((), jnp.float32)
            zero_i = jnp.zeros((), jnp.int32)
            aux: list = []
            if last:
                # ys is already this device's label-row shard (shard_batch)
                labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                contrib = jnp.zeros((A,), cdtype)
                if fused:
                    xc = cast_input(x, cdtype)
                    if train:
                        with collect_aux_losses(aux):
                            (obj_sum, ce_sum, correct,
                             new_states) = fused_slice_loss_sums(
                                layers, params, states, xc, labels, smooth)
                        correct5 = zero_i
                        valid = jnp.sum((labels >= 0).astype(jnp.int32))
                    else:
                        ce_sum, correct, correct5, valid = (
                            fused_slice_eval_sums(layers, params, states, xc,
                                                  labels))
                        obj_sum = ce_sum
                        new_states = states
                else:
                    with collect_aux_losses(aux):
                        y, new_states = apply_slice(
                            layers, params, states, cast_input(x, cdtype),
                            train)
                    logits = y.astype(jnp.float32)
                    logp = jax.nn.log_softmax(logits, axis=-1)
                    mask = (labels >= 0)
                    safe = jnp.maximum(labels, 0)
                    nll = -jnp.take_along_axis(logp, safe[..., None],
                                               axis=-1)[..., 0]
                    obj_tok = ((1.0 - smooth) * nll
                               - smooth * jnp.mean(logp, axis=-1)
                               if smooth else nll)
                    fmask = mask.astype(jnp.float32)
                    ce_sum = jnp.sum(nll * fmask)
                    obj_sum = jnp.sum(obj_tok * fmask)
                    correct = correct_and_count(logits, labels)[0]
                    correct5 = (zero_i if train
                                else correct_topk(logits, labels))
                    valid = jnp.sum(mask.astype(jnp.int32))
            else:
                with collect_aux_losses(aux):
                    y, new_states = apply_slice(layers, params, states,
                                                cast_input(x, cdtype), train)
                obj_sum = ce_sum = zero_f
                correct = correct5 = valid = zero_i
                contrib = jnp.zeros((A,), cdtype)
                yflat = y.astype(cdtype).reshape(-1)
                contrib = lax.dynamic_update_slice(
                    contrib, yflat, (rep * rows * out_elem,))
            # replica k saw 1/r of the rows: average mean-style MoE aux over
            # the replica group so the 'pipe' psum yields the stage mean
            aux_sum = sum(aux, jnp.float32(0.0)) / r
            new_state_row = pad_vec(
                ravel_pytree(new_states)[0].astype(jnp.float32),
                state_row.shape[0])
            return tuple(
                jax.tree.map(_vary, (contrib, new_state_row, obj_sum, ce_sum,
                                     aux_sum, correct, correct5, valid)))

        if train and self.cfg.remat_stages:
            branch = jax.checkpoint(branch)
        return branch

    # -- compiled steps ----------------------------------------------------

    def _build_steps(self):
        self._row_sharding = NamedSharding(self.mesh, P("pipe", None))
        self._repl_sharding = NamedSharding(self.mesh, P())
        self._data_sharding = NamedSharding(self.mesh, P("pipe"))
        self._group_sum = self._make_group_reduce(mean=False)
        self._group_mean = self._make_group_reduce(mean=True)
        self.train_step = self._make_train_step()
        self.eval_step = self._make_eval_step()
        self._built = True

    def _make_pipe_fn(self, train: bool):
        S, M, A, N, R = (self.num_stages, self.num_microbatches,
                         self._act_size, self.N, self._R)
        aux_w = self.cfg.moe_aux_weight if train else 0.0
        branches = [self._make_branch(s, train) for s in range(S)]
        chain = [(i, i + 1) for i in range(N - 1)]
        stage_tbl = jnp.asarray(self._stage_of)
        rep_tbl = jnp.asarray(self._rep_of)
        accept_tbl = jnp.asarray(self._accept)
        cdtype = self.compute_dtype

        def inner(params_rows, state_rows, xs_rows, ys_rows):
            param_row = _vary(params_rows[0])
            st_row = _vary(state_rows[0])
            xs = _vary(xs_rows[0])  # this device's [M, rows0, ...] shard
            ys = _vary(ys_rows[0])
            d = lax.axis_index("pipe")
            stage = stage_tbl[d]
            rep = rep_tbl[d]
            acc_row = accept_tbl[d]  # [R] bool
            T = M + S - 1

            def body(carry, t):
                (in_total, st_row, obj_a, ce_a, aux_a, corr_a, corr5_a,
                 val_a) = carry
                u = t - stage
                valid = (u >= 0) & (u < M)
                m = jnp.clip(u, 0, M - 1)
                (contrib, new_st, obj_s, ce_s, aux_s, corr, corr5,
                 val) = lax.switch(stage, branches, param_row, st_row,
                                   in_total, xs, ys, m, rep)
                st_row = jnp.where(valid, new_st, st_row)
                fvalid = valid.astype(jnp.float32)
                obj_a = obj_a + fvalid * obj_s
                ce_a = ce_a + fvalid * ce_s
                aux_a = aux_a + fvalid * aux_s
                ivalid = valid.astype(jnp.int32)
                corr_a = corr_a + ivalid * corr
                corr5_a = corr5_a + ivalid * corr5
                val_a = val_a + ivalid * val
                # conveyor: R rounds of the right-shift chain; the static
                # accept row picks out this device's input-boundary payloads
                buf = jnp.where(valid, contrib, jnp.zeros_like(contrib))
                nxt = _vary(jnp.zeros((A,), cdtype))
                for rnd in range(R):
                    buf = lax.ppermute(buf, "pipe", chain)
                    nxt = jnp.where(acc_row[rnd], nxt + buf, nxt)
                out = (nxt, st_row, obj_a, ce_a, aux_a, corr_a, corr5_a,
                       val_a)
                return tuple(jax.tree.map(_vary, out)), None

            init_carry = tuple(jax.tree.map(_vary, (
                jnp.zeros((A,), cdtype),
                st_row,
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32),
            )))
            (_, st_row, obj_a, ce_a, aux_a, corr_a, corr5_a, val_a) = (
                lax.scan(body, init_carry, jnp.arange(T))[0])
            obj = lax.psum(obj_a, "pipe")
            ce = lax.psum(ce_a, "pipe")
            aux = lax.psum(aux_a, "pipe") / M
            correct = lax.psum(corr_a, "pipe")
            correct5 = lax.psum(corr5_a, "pipe")
            valid = lax.psum(val_a, "pipe")
            denom = jnp.maximum(1.0, valid.astype(jnp.float32))
            # objective: global mean over valid labels + weighted MoE aux
            obj = obj / denom + aux_w * aux
            ce = ce / denom
            return obj, ce, st_row[None], correct, correct5, valid

        return _shard_map(
            inner,
            mesh=self.mesh,
            in_specs=(P("pipe", None), P("pipe", None), P("pipe"), P("pipe")),
            out_specs=(P(), P(), P("pipe", None), P(), P(), P()),
        )

    def _make_group_reduce(self, mean: bool):
        """Subgroup ring allreduce over each stage's replica range ([N, X]
        rows -> per-row group sum or mean)."""
        N = self.N
        repl, offsets, stage_of = self.repl, self._offsets, self._stage_of
        ring = []
        for s, r in enumerate(repl):
            off = offsets[s]
            for k in range(r):
                ring.append((off + k, off + (k + 1) % r))
        Rg = max(repl) - 1
        gsize_tbl = jnp.asarray(
            np.array([repl[stage_of[d]] for d in range(N)], np.int32))

        def inner(rows):
            x = _vary(rows[0])
            d = lax.axis_index("pipe")
            g = gsize_tbl[d]
            carry = x
            total = x
            for t in range(Rg):
                carry = lax.ppermute(carry, "pipe", ring)
                total = jnp.where(t < g - 1, total + carry, total)
            if mean:
                total = total / g.astype(total.dtype)
            return total[None]

        if Rg == 0:
            return lambda rows: rows
        return _shard_map(inner, mesh=self.mesh,
                          in_specs=(P("pipe", None),),
                          out_specs=P("pipe", None))

    @property
    def _total_samples(self) -> int:
        return self.num_microbatches * self.mb

    def _ts_sharding(self):
        sh = self._row_sharding
        return HeteroTrainState(sh, sh, sh)

    def _make_train_step(self):
        pipe_train = self._make_pipe_fn(train=True)

        def train_step(ts: HeteroTrainState, xs, ys, valid_mb, lr):
            # valid_mb (the [M] full-microbatch valid counts) serves the
            # async engine's per-microbatch objective; the sync objective
            # normalizes by the psum'd global count instead
            def loss_fn(params_mat):
                obj, ce, new_state, correct, _c5, valid = pipe_train(
                    params_mat, ts.model_state, xs, ys)
                return obj, (ce, new_state, correct, valid)

            (_, (ce, new_state, correct, valid)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(ts.params)
            # each replica row's grad covers only its row-shard of the batch;
            # the stage gradient is the sum over the replica group (the
            # reference's per-stage DDP allreduce, runtime.py:232-263)
            grads = self._group_sum(grads)
            # keep BN running stats identical across a stage's replica rows
            new_state = self._group_mean(new_state)
            params, opt = self._opt_update(ts.params, grads, ts.opt, lr)
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            return HeteroTrainState(params, new_state, opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._data_sharding,
                          self._data_sharding, self._repl_sharding, None),
        )

    def _make_eval_step(self):
        pipe_eval = self._make_pipe_fn(train=False)

        def eval_step(ts, xs, ys, valid_mb):
            _, ce, _, correct, correct5, valid = pipe_eval(
                ts.params, ts.model_state, xs, ys)
            return {
                "loss": ce,
                "correct": correct,
                "correct5": correct5,
                "count": valid,
            }

        return jax.jit(
            eval_step,
            in_shardings=(self._ts_sharding(), self._data_sharding,
                          self._data_sharding, self._repl_sharding),
        )

    # -- data placement ----------------------------------------------------

    def shard_batch(self, x, y):
        """Global batch [M*mb, ...] -> per-device row slices on the 'pipe'
        axis (VERDICT r2 #6: no full-batch replication). Device d holds ONLY
        what it consumes: its stage-0 input rows [rep*mb/r0, (rep+1)*mb/r0)
        per microbatch (zeros off stage 0) and its last-stage label rows
        (zeros elsewhere) — the reference shards its first-stage DataLoader
        the same way (main_with_runtime.py:351-363). The async engine's
        per-microbatch loss denominators (valid counts over the FULL
        microbatch) can't be derived from a label shard, so they ship as a
        tiny replicated [M] vector."""
        from ddlbench_tpu.distributed import put_global_batch

        M, mb, N = self.num_microbatches, self.mb, self.N
        # host-side assembly: device_put of a numpy array with a sharding
        # transfers each device ONLY its shard — never staging the stacked
        # [N, ...] buffer (or its zero rows) on one device
        x = np.asarray(x).reshape(M, mb, *x.shape[1:])
        y = np.asarray(y).reshape(M, mb, *y.shape[1:])
        r0, rL = self.repl[0], self.repl[-1]
        rows0, rowsL = mb // r0, mb // rL
        xs_p = np.zeros((N, M, rows0, *x.shape[2:]), x.dtype)
        ys_p = np.full((N, M, rowsL, *y.shape[2:]), -1, y.dtype)
        for d in range(N):
            s, k = self._stage_of[d], self._rep_of[d]
            if s == 0:
                xs_p[d] = x[:, k * rows0:(k + 1) * rows0]
            if s == self.num_stages - 1:
                ys_p[d] = y[:, k * rowsL:(k + 1) * rowsL]
        valid = np.maximum(1.0, np.sum(
            (y >= 0).reshape(M, -1).astype(np.float32), axis=1))  # [M]
        return (
            put_global_batch(xs_p, self._data_sharding),
            put_global_batch(ys_p, self._data_sharding),
            put_global_batch(valid, self._repl_sharding),
        )

    @property
    def world_size(self) -> int:
        return self.N


def _bwd_accept_table(repl: Sequence[int], R: int):
    """Backward-conveyor accept table: device d of stage s accepts payloads
    originating from stage s+1's devices (the left-shift chain delivers the
    round-0 injection of device d+(t+1) at round t)."""
    S = len(repl)
    offsets = [0]
    for r in repl:
        offsets.append(offsets[-1] + r)
    N = offsets[-1]
    accept = np.zeros((N, max(R, 1)), bool)
    for d in range(N):
        s = next(i for i in range(S) if offsets[i] <= d < offsets[i + 1])
        if s == S - 1:
            continue
        lo, hi = offsets[s + 1], offsets[s + 1] + repl[s + 1]
        for t in range(R):
            origin = d + (t + 1)
            if lo <= origin < hi:
                accept[d, t] = True
    return accept


class HeteroPipeDreamStrategy(HeteroGPipeStrategy):
    """strategy='pipedream' with uneven ``stage_replication`` — async 1F1B +
    weight stashing over the flat 'pipe' axis.

    Because replication is intra-stage batch splitting, every stage processes
    every microbatch and the uniform 1F1B timetable (parallel/pipedream.py
    fwd_mb_at/bwd_mb_at) applies unchanged; per-microbatch updates follow
    each backward with the stage gradient ring-summed over the replica group
    (the reference's per-stage DDP, runtime.py:232-263). The semantics are
    therefore IDENTICAL to the uniform PipeDream strategy's — the event-replay
    simulator of tests/test_pipedream.py verifies hetero runs unchanged —
    where the reference's whole-minibatch round-robin gives each replica a
    different minibatch stream.

    Collectives (both conveyors and the gradient ring) run unconditionally
    every half-tick with masked payloads: stages disagree about fwd/bwd
    validity at a tick, so a collective under lax.cond would deadlock the
    lockstep program.
    """

    def _make_stage_fwd(self, s: int):
        """(param_row, state_row, x_rows) -> (y_rows, new_state_row, aux)."""
        from ddlbench_tpu.models.moe import collect_aux_losses

        layers = self.model.layers[self.bounds[s]:self.bounds[s + 1]]
        p_unravel, p_len = self._p_unravels[s], self._p_lens[s]
        s_unravel, s_len = self._s_unravels[s], self._s_lens[s]
        cdtype = self.compute_dtype

        def stage_fwd(param_row, state_row, x):
            params = cast_params(p_unravel(param_row[:p_len]), cdtype)
            states = s_unravel(state_row[:s_len])
            aux: list = []
            with collect_aux_losses(aux):
                y, new_states = apply_slice(layers, params, states,
                                            cast_input(x, cdtype), True)
            new_state_row = pad_vec(
                ravel_pytree(new_states)[0].astype(jnp.float32),
                state_row.shape[0])
            return y, new_state_row, sum(aux, jnp.float32(0.0))

        return stage_fwd

    def _make_head_fns(self, s: int):
        """Fused projection+loss twins of _make_stage_fwd for the last stage
        (parallel/common.fused_slice_loss_sums calling convention — no
        [rows, V] logits materialize)."""
        from ddlbench_tpu.models.moe import collect_aux_losses

        layers = self.model.layers[self.bounds[s]:self.bounds[s + 1]]
        p_unravel, p_len = self._p_unravels[s], self._p_lens[s]
        s_unravel, s_len = self._s_unravels[s], self._s_lens[s]
        cdtype = self.compute_dtype
        smooth = self.cfg.resolved_label_smoothing()

        def unpack(param_row, state_row):
            return (cast_params(p_unravel(param_row[:p_len]), cdtype),
                    s_unravel(state_row[:s_len]))

        def fused_metrics(param_row, state_row, x, labels):
            """Forward-side metrics: (ce_sum, correct, valid, new_state_row)."""
            params, states = unpack(param_row, state_row)
            _, ce_sum, correct, new_states = fused_slice_loss_sums(
                layers, params, states, cast_input(x, cdtype), labels, smooth)
            new_state_row = pad_vec(
                ravel_pytree(new_states)[0].astype(jnp.float32),
                state_row.shape[0])
            valid = jnp.sum((labels >= 0).astype(jnp.int32))
            return ce_sum, correct, valid, new_state_row

        def fused_obj(param_row, state_row, x, labels):
            """Backward-side objective: (obj_sum, aux_sum) — differentiable
            in param_row and x."""
            params, states = unpack(param_row, state_row)
            aux: list = []
            with collect_aux_losses(aux):
                obj_sum, _, _, _ = fused_slice_loss_sums(
                    layers, params, states, cast_input(x, cdtype), labels,
                    smooth)
            return obj_sum, sum(aux, jnp.float32(0.0))

        return fused_metrics, fused_obj

    def _make_train_step(self):
        from ddlbench_tpu.parallel.pipedream import bwd_mb_at, fwd_mb_at

        S, M, mb, N = self.num_stages, self.num_microbatches, self.mb, self.N
        H = 2 * M + 2 * S - 2
        NSLOT = min(S, M)
        A, R = self._act_size, self._R
        repl, bounds, offsets = self.repl, self.bounds, self._offsets
        opt_update = self._opt_update
        smooth = self.cfg.resolved_label_smoothing()
        aux_w = self.cfg.moe_aux_weight
        cdtype = self.compute_dtype
        chain_f = [(i, i + 1) for i in range(N - 1)]
        chain_b = [(i + 1, i) for i in range(N - 1)]
        ring = []
        for s, r in enumerate(repl):
            off = offsets[s]
            for k in range(r):
                ring.append((off + k, off + (k + 1) % r))
        Rg = max(repl) - 1
        stage_tbl = jnp.asarray(self._stage_of)
        rep_tbl = jnp.asarray(self._rep_of)
        acc_f_tbl = jnp.asarray(self._accept)
        acc_b_tbl = jnp.asarray(_bwd_accept_table(repl, R))
        gsize_tbl = jnp.asarray(
            np.array([repl[self._stage_of[d]] for d in range(N)], np.int32))
        stage_fwds = [self._make_stage_fwd(s) for s in range(S)]
        head_fns = self._make_head_fns(S - 1) if self._fused else None
        if head_fns is not None and self.cfg.remat_stages:
            # the backward-side objective is the one jax.grad traces: remat
            # it like stage_fwd so the last stage's layers[:-1] activations
            # are recomputed, not stored (the metrics twin is never
            # differentiated)
            head_fns = (head_fns[0], jax.checkpoint(head_fns[1]))
        in_shapes = [self.shapes[bounds[s]] for s in range(S)]
        in_elems = [math.prod(sh) for sh in in_shapes]
        rows_of = [mb // r for r in repl]

        def make_branch(s: int):
            stage_fwd = stage_fwds[s]
            if self.cfg.remat_stages:
                stage_fwd = jax.checkpoint(stage_fwd)
            rows = rows_of[s]
            in_elem = in_elems[s]
            in_shape = in_shapes[s]
            last = s == S - 1
            fused = last and self._fused
            # replica s sees 1/r of the rows: scale mean-style MoE aux so the
            # replica-group gradient sum recovers the stage mean
            aux_w_s = aux_w / repl[s]
            if not last:
                out_elem = in_elems[s + 1]

            def slice_rows(buf, rep, elem, nrows, shape):
                flat = lax.dynamic_slice(
                    buf, (rep * nrows * elem,), (nrows * elem,))
                return flat.reshape(nrows, *shape)

            def branch(carry, xs, ys, valid_mb, h, lr, rep):
                (params, opt_row, st_row, stash_p, stash_x, fwd_q,
                 g_in, loss_acc, corr_acc, val_acc) = carry

                f, valid_f = fwd_mb_at(s, S, M, h)
                b, valid_b = bwd_mb_at(s, S, M, h)

                # ---- forward (newest params; stash weights + input rows) --
                def do_fwd(op):
                    params, st_row, stash_p, stash_x, fwd_q = op
                    if s == 0:
                        # xs is already this device's row shard (shard_batch)
                        x = lax.dynamic_index_in_dim(xs, f, keepdims=False)
                    else:
                        x = slice_rows(
                            lax.dynamic_index_in_dim(fwd_q, f % 2,
                                                     keepdims=False),
                            rep, in_elem, rows, in_shape)
                    if fused:
                        labels = lax.dynamic_index_in_dim(ys, f,
                                                          keepdims=False)
                        ce_sum, corr, val, new_st = head_fns[0](
                            params, st_row, x, labels)
                        y_out = jnp.zeros((A,), cdtype)
                    elif last:
                        y, new_st, _aux = stage_fwd(params, st_row, x)
                        labels = lax.dynamic_index_in_dim(ys, f,
                                                          keepdims=False)
                        logits = y.astype(jnp.float32)
                        logp = jax.nn.log_softmax(logits, axis=-1)
                        mask = labels >= 0
                        safe = jnp.maximum(labels, 0)
                        nll = -jnp.take_along_axis(
                            logp, safe[..., None], axis=-1)[..., 0]
                        ce_sum = jnp.sum(nll * mask.astype(jnp.float32))
                        corr = correct_and_count(logits, labels)[0]
                        val = jnp.sum(mask.astype(jnp.int32))
                        y_out = jnp.zeros((A,), cdtype)
                    else:
                        y, new_st, _aux = stage_fwd(params, st_row, x)
                        ce_sum = jnp.zeros((), jnp.float32)
                        corr = jnp.zeros((), jnp.int32)
                        val = jnp.zeros((), jnp.int32)
                        y_out = jnp.zeros((A,), cdtype)
                        y_out = lax.dynamic_update_slice(
                            y_out, y.astype(cdtype).reshape(-1),
                            (rep * rows * out_elem,))
                    slot = f % NSLOT
                    stash_p = lax.dynamic_update_index_in_dim(
                        stash_p, params, slot, 0)
                    if s != 0:
                        # stage 0's rows are re-sliced from xs at backward
                        # time (exact for int tokens, saves a stash write)
                        x_keep = pad_vec(x.astype(cdtype).reshape(-1), A)
                        stash_x = lax.dynamic_update_index_in_dim(
                            stash_x, x_keep, slot, 0)
                    return jax.tree.map(
                        _vary, (new_st, stash_p, stash_x, y_out, ce_sum,
                                corr, val))

                def skip_fwd(op):
                    params, st_row, stash_p, stash_x, fwd_q = op
                    return jax.tree.map(
                        _vary,
                        (st_row, stash_p, stash_x, jnp.zeros((A,), cdtype),
                         jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32),
                         jnp.zeros((), jnp.int32)))

                st_row, stash_p, stash_x, y_out, ce_mb, corr_mb, val_mb = (
                    lax.cond(valid_f, do_fwd, skip_fwd,
                             (params, st_row, stash_p, stash_x, fwd_q)))
                loss_acc = loss_acc + ce_mb
                corr_acc = corr_acc + corr_mb
                val_acc = val_acc + val_mb

                # ---- backward (stashed weights + stashed input rows) ------
                # No collectives in here: gp is ring-summed by the caller.
                def do_bwd(op):
                    params, st_row, stash_p, stash_x, g_in = op
                    slot = b % NSLOT
                    p_st = lax.dynamic_index_in_dim(stash_p, slot,
                                                    keepdims=False)
                    if s == 0:
                        # xs is already this device's row shard (shard_batch)
                        x_st = lax.dynamic_index_in_dim(xs, b, keepdims=False)
                    else:
                        x_st = lax.dynamic_slice(
                            lax.dynamic_index_in_dim(stash_x, slot,
                                                     keepdims=False),
                            (0,), (rows * in_elem,)).reshape(rows, *in_shape)
                    if last:
                        labels = lax.dynamic_index_in_dim(ys, b,
                                                          keepdims=False)
                        # per-microbatch mean over the FULL microbatch's
                        # valid labels (shipped as the replicated valid_mb
                        # vector — a label shard can't derive it) so the
                        # replica-summed gradient equals the uniform
                        # pipedream's per-mb objective
                        denom = valid_mb[b]

                        if fused:
                            def loss_of(pv, xv):
                                obj_sum, aux = head_fns[1](pv, st_row, xv,
                                                           labels)
                                return obj_sum / denom + aux_w_s * aux
                        else:
                            def loss_of(pv, xv):
                                y, _, aux = stage_fwd(pv, st_row, xv)
                                logits = y.astype(jnp.float32)
                                logp = jax.nn.log_softmax(logits, axis=-1)
                                mask = (labels >= 0).astype(jnp.float32)
                                safe = jnp.maximum(labels, 0)
                                nll = -jnp.take_along_axis(
                                    logp, safe[..., None], axis=-1)[..., 0]
                                if smooth:
                                    nll = ((1.0 - smooth) * nll - smooth
                                           * jnp.mean(logp, axis=-1))
                                return (jnp.sum(nll * mask) / denom
                                        + aux_w_s * aux)
                        if s == 0:
                            gp = jax.grad(lambda pv: loss_of(pv, x_st))(p_st)
                            gx = None
                        else:
                            gp, gx = jax.grad(loss_of, argnums=(0, 1))(
                                p_st, x_st)
                    else:
                        def fwd_of(pv, xv):
                            y, _, aux = stage_fwd(pv, st_row, xv)
                            return y, aux

                        g_rows = slice_rows(g_in, rep, out_elem, rows,
                                            in_shapes[s + 1])
                        if s == 0:
                            (y, aux), vjp_fn = jax.vjp(
                                lambda pv: fwd_of(pv, x_st), p_st)
                            (gp,) = vjp_fn((g_rows.astype(y.dtype),
                                            jnp.float32(aux_w_s)))
                            gx = None
                        else:
                            (y, aux), vjp_fn = jax.vjp(fwd_of, p_st, x_st)
                            gp, gx = vjp_fn((g_rows.astype(y.dtype),
                                             jnp.float32(aux_w_s)))
                    gx_out = (jnp.zeros((A,), cdtype) if gx is None else
                              lax.dynamic_update_slice(
                                  jnp.zeros((A,), cdtype),
                                  gx.astype(cdtype).reshape(-1),
                                  (rep * rows * in_elem,)))
                    return jax.tree.map(_vary, (gp, gx_out))

                def skip_bwd(op):
                    params, st_row, stash_p, stash_x, g_in = op
                    return jax.tree.map(
                        _vary, (jnp.zeros_like(params),
                                jnp.zeros((A,), cdtype)))

                gp, gx_out = lax.cond(
                    valid_b, do_bwd, skip_bwd,
                    (params, st_row, stash_p, stash_x, g_in))

                return (params, opt_row, st_row, stash_p, stash_x, fwd_q,
                        gp, gx_out, y_out, _vary(valid_b),
                        loss_acc, corr_acc, val_acc)

            return branch

        branches = [make_branch(s) for s in range(S)]

        def inner(params_rows, state_rows, opt_rows, xs_rows, ys_rows,
                  valid_mb, lr):
            params = _vary(params_rows[0])
            st_row = _vary(state_rows[0])
            opt_row = jax.tree.map(lambda a: _vary(a[0]), opt_rows)
            xs = _vary(xs_rows[0])  # this device's [M, rows, ...] shard
            ys = _vary(ys_rows[0])
            d = lax.axis_index("pipe")
            stage = stage_tbl[d]
            rep = rep_tbl[d]
            acc_f = acc_f_tbl[d]
            acc_b = acc_b_tbl[d]
            gsize = gsize_tbl[d]
            L = params.shape[0]

            def body(carry, h):
                (params, opt_row, st_row, stash_p, stash_x, fwd_q,
                 x_in, g_in, loss_acc, corr_acc, val_acc) = carry

                # absorb the activation that arrived last tick into the
                # 2-slot queue, keyed by the producing stage's schedule
                def absorb(s):
                    if s == 0:
                        return (jnp.zeros((), jnp.int32),
                                jnp.zeros((), jnp.bool_))
                    return fwd_mb_at(s - 1, S, M, h - 1)

                f_in, valid_in = lax.switch(
                    stage,
                    [(lambda s=s: jax.tree.map(_vary, absorb(s)))
                     for s in range(S)])
                fwd_q = jnp.where(
                    valid_in,
                    lax.dynamic_update_index_in_dim(fwd_q, x_in, f_in % 2, 0),
                    fwd_q)

                carry2 = (params, opt_row, st_row, stash_p, stash_x, fwd_q,
                          g_in, loss_acc, corr_acc, val_acc)
                (params, opt_row, st_row, stash_p, stash_x, fwd_q, gp,
                 gx_out, y_out, valid_b, loss_acc, corr_acc, val_acc) = (
                    lax.switch(stage, branches, carry2, xs, ys, valid_mb,
                               h, lr, rep))

                # ---- per-stage gradient ring-sum + gated update ----------
                gp = jnp.where(valid_b, gp, jnp.zeros_like(gp))
                carry_g = gp
                total_g = gp
                for t in range(Rg):
                    carry_g = lax.ppermute(carry_g, "pipe", ring)
                    total_g = jnp.where(t < gsize - 1, total_g + carry_g,
                                        total_g)
                new_params, new_opt = opt_update(
                    params, total_g.astype(jnp.float32), opt_row, lr)
                params = jnp.where(valid_b, new_params, params)
                opt_row = jax.tree.map(
                    lambda a, b_: jnp.where(valid_b, a, b_),
                    new_opt, opt_row)

                # ---- conveyors -------------------------------------------
                buf = y_out
                x_next = _vary(jnp.zeros((A,), cdtype))
                g_next = _vary(jnp.zeros((A,), cdtype))
                gbuf = gx_out
                for rnd in range(R):
                    if chain_f:
                        buf = lax.ppermute(buf, "pipe", chain_f)
                        gbuf = lax.ppermute(gbuf, "pipe", chain_b)
                    x_next = jnp.where(acc_f[rnd], x_next + buf, x_next)
                    g_next = jnp.where(acc_b[rnd], g_next + gbuf, g_next)

                out = (params, opt_row, st_row, stash_p, stash_x, fwd_q,
                       x_next, g_next, loss_acc, corr_acc, val_acc)
                return jax.tree.map(_vary, out), None

            zeros_A = _vary(jnp.zeros((A,), cdtype))
            init_carry = jax.tree.map(_vary, (
                params, opt_row, st_row,
                jnp.zeros((NSLOT, L), jnp.float32),
                jnp.zeros((NSLOT, A), cdtype),
                jnp.zeros((2, A), cdtype),
                zeros_A, zeros_A,
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.int32),
                jnp.zeros((), jnp.int32),
            ))
            (params, opt_row, st_row, *_rest, loss_acc, corr_acc,
             val_acc) = lax.scan(body, init_carry, jnp.arange(H))[0]
            ce = lax.psum(loss_acc, "pipe")
            correct = lax.psum(corr_acc, "pipe")
            valid = lax.psum(val_acc, "pipe")
            return (params[None], st_row[None],
                    jax.tree.map(lambda a: a[None], opt_row),
                    ce, correct, valid)

        pipe = _shard_map(
            inner,
            mesh=self.mesh,
            in_specs=(P("pipe", None), P("pipe", None), P("pipe", None),
                      P("pipe"), P("pipe"), P(), P()),
            out_specs=(P("pipe", None), P("pipe", None), P("pipe", None),
                       P(), P(), P()),
        )

        def train_step(ts: HeteroTrainState, xs, ys, valid_mb, lr):
            params, st, opt, ce, correct, valid = pipe(
                ts.params, ts.model_state, ts.opt, xs, ys, valid_mb, lr)
            # replicas saw different row-shards: sync BN running stats
            st = self._group_mean(st)
            fvalid = jnp.maximum(1.0, valid.astype(jnp.float32))
            metrics = {
                "loss": ce / fvalid,
                "accuracy": correct.astype(jnp.float32) / fvalid,
            }
            return HeteroTrainState(params, st, opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._data_sharding,
                          self._data_sharding, self._repl_sharding, None),
        )
