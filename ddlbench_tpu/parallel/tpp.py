"""Composed tensor x pipeline (x data) parallelism — Megatron TP inside
GPipe stages, with an optional DP axis on top (full 3-D parallelism).

No reference analog: sara-nl/DDLBench composes pipelining with DATA
parallelism only (run_template.sh's straggler/hybrid plans; SURVEY.md §2 E5)
— tensor parallelism is listed in SURVEY.md §2 E7 as a new-capability
recommendation. This module composes all three TPU-natively on one mesh:

* mesh axes ``('data', 'stage', 'model')`` — 'model' is innermost so a
  stage's TP group sits on adjacent ICI neighbors (the TP psums are the
  bandwidth-hungry collectives; the per-tick stage handoff moves one
  activation buffer; the DP gradient all-reduce is once per step and may
  span DCN).
* The pipeline is the gpipe scan (lax.scan over M + S - 1 ticks,
  lax.switch per stage, ppermute handoffs — parallel/gpipe.py); inside a
  stage every transformer block runs Megatron-sliced under the
  ``tensor_parallel`` trace context (models/transformer.py): each 'model'
  shard computes its local contiguous head group and MLP column block, and
  the two row-parallel projections ``lax.psum`` over 'model'.
* Parameters ride TWO packed matrices (parallel/packing.py): the sliced
  leaves as ``[S, tp, L_sl]`` sharded ``P('stage', 'model')`` — each device
  holds exactly its (stage, shard) slice — and the shared leaves (LN
  scales/biases, output bias, embeddings, LM head) as ``[S, L_rp]`` sharded
  ``P('stage')``, replicated across the 'model' axis. Each matrix is
  ``pcast`` to varying over exactly the axes its in_spec does NOT name, so
  shard_map's transpose inserts exactly the right gradient all-reduces —
  over 'model' for the shared leaves (Megatron's LN/embedding sync) and
  over 'data' for both matrices (the DP all-reduce) — the same mechanism
  gpipe uses. Activations are replicated across 'model' (Megatron's design
  point), so correctness does not depend on any other collective.
* The batch shards over 'data' exactly as in gpipe: the global batch is
  ``M * mb * dp`` with each data replica running ``mb`` rows of every
  microbatch.

Scope: the synchronous (gpipe) schedule, V=1, unfused CE head. Selected by
``RunConfig.tp_size > 1`` with strategy='gpipe' (parallel/api.py);
``dp_replicas > 1`` adds the data axis (num_devices = dp x stages x tp).
"""

from __future__ import annotations

import math
from typing import Any, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, apply_slice, init_model
from ddlbench_tpu.models.transformer import (tensor_parallel,
                                             tp_split_layer_params)
from ddlbench_tpu.parallel.common import (
    cast_input, cast_params, correct_and_count, correct_topk,
    cross_entropy_loss, vary as _vary_axes)
from ddlbench_tpu.parallel.gpipe import _shard_map
from ddlbench_tpu.parallel.packing import (
    balanced_stage_bounds, layer_flop_costs, pack_stage, pad_vec)

_AXES = ("data", "stage", "model")


def _vary(v, axes=_AXES):
    return _vary_axes(v, axes)


class TPPipeTrainState(NamedTuple):
    # params = {"sliced": [S, tp, L_sl] P('stage','model'),
    #           "repl":   [S, L_rp]     P('stage')}
    params: Any
    model_state: jax.Array  # [S, L_st] P('stage')
    opt: Any  # {"sliced": opt-dict, "repl": opt-dict} (make_optimizer x2)


class TPGPipeStrategy:
    """strategy='gpipe' + tp_size>1: Megatron-sliced stages on a
    ('stage', 'model') mesh."""

    def __init__(self, model: LayerModel, cfg: RunConfig,
                 devices: Optional[Sequence[jax.Device]] = None,
                 stage_bounds: Optional[List[int]] = None):
        from ddlbench_tpu.distributed import make_mesh
        from ddlbench_tpu.parallel.common import make_optimizer

        self.model = model
        self.cfg = cfg
        self.tp = cfg.tp_size
        self.dp = max(1, cfg.dp_replicas)
        self.num_stages = cfg.resolved_stages()
        assert self.tp > 1, "use GPipeStrategy for tp_size == 1"
        self.mesh = make_mesh(
            [("data", self.dp), ("stage", self.num_stages),
             ("model", self.tp)],
            devices=devices, dcn_axis="data")
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self.mb, self.num_microbatches = cfg.resolved_batches()
        self._stage_bounds_override = stage_bounds
        self._built = False
        self._opt_init, self._opt_update = make_optimizer(cfg)
        from ddlbench_tpu.guard import device_guard

        self._guard = device_guard(cfg)  # None = pre-guard program
        from ddlbench_tpu.parallel.common import head_fusable

        if cfg.fused_head_loss and head_fusable(model):
            # default-on flag, so a hard validate() error would hit every
            # tpp run; surface the scope limit instead of silently differing
            # from plain gpipe's fused path. stderr: stdout carries the
            # machine-scraped result/JSON lines (advisor r5).
            import sys

            print("tpp: fused projection+loss head is not supported under "
                  "tp_size > 1; using the unfused CE head", file=sys.stderr,
                  flush=True)

    # -- initialization ----------------------------------------------------

    def init(self, key) -> TPPipeTrainState:
        params_list, state_list, shapes = init_model(self.model, key)
        S, tp = self.num_stages, self.tp
        bounds = getattr(self, "bounds", None)
        if bounds is None:
            if self._stage_bounds_override is not None:
                bounds = list(self._stage_bounds_override)
            else:
                costs = layer_flop_costs(params_list, shapes,
                                         self.model.layers)
                bounds = balanced_stage_bounds(costs, S)
            assert (len(bounds) == S + 1 and bounds[0] == 0
                    and bounds[-1] == len(self.model.layers))
            self.bounds = bounds
            self.shapes = shapes

        sl_rows, rp_vecs = [], []
        sl_unravels, sl_lens = [], []
        rp_unravels, rp_lens = [], []
        st_vecs, st_unravels, st_lens = [], [], []
        any_sliced = False
        for c in range(S):
            chunk = params_list[bounds[c]:bounds[c + 1]]
            splits = [tp_split_layer_params(p, tp) for p in chunk]
            any_sliced |= any(bool(sh[0]) for sh, _ in splits)
            shard_trees = [[sh[s] for sh, _ in splits] for s in range(tp)]
            repl_tree = [rp for _, rp in splits]
            vecs = [pack_stage(t) for t in shard_trees]
            # identical structure across shards: one unravel serves all
            sl_rows.append([v for v, _, _ in vecs])
            sl_unravels.append(vecs[0][1])
            sl_lens.append(vecs[0][2])
            v, u, n = pack_stage(repl_tree)
            rp_vecs.append(v)
            rp_unravels.append(u)
            rp_lens.append(n)
            v, u, n = pack_stage(state_list[bounds[c]:bounds[c + 1]])
            st_vecs.append(v)
            st_unravels.append(u)
            st_lens.append(n)
        if not any_sliced:
            raise ValueError(
                f"tp_size={tp}: no layer of {self.model.name} is "
                f"TP-shardable (models/transformer.tp_split_layer_params)")

        L_sl = max(max(r.size for r in rows) for rows in sl_rows)
        sliced_mat = jnp.stack([
            jnp.stack([jnp.pad(r, (0, L_sl - r.size)) for r in rows])
            for rows in sl_rows])  # [S, tp, L_sl]
        L_rp = max(v.size for v in rp_vecs)
        repl_mat = jnp.stack([jnp.pad(v, (0, L_rp - v.size))
                              for v in rp_vecs])  # [S, L_rp]
        L_st = max(v.size for v in st_vecs)
        state_mat = jnp.stack([jnp.pad(v, (0, L_st - v.size))
                               for v in st_vecs])  # [S, L_st]

        if not self._built:
            self._sl_unravels, self._sl_lens = sl_unravels, sl_lens
            self._rp_unravels, self._rp_lens = rp_unravels, rp_lens
            self._st_unravels, self._st_lens = st_unravels, st_lens
            interior = [self.mb * math.prod(shapes[bounds[c]])
                        for c in range(1, S)]
            self._act_size = max(interior) if interior else 1
            self._build_steps()

        from ddlbench_tpu.distributed import put_global_batch

        sl_sh = NamedSharding(self.mesh, P("stage", "model", None))
        rp_sh = NamedSharding(self.mesh, P("stage", None))
        params = {
            "sliced": put_global_batch(sliced_mat, sl_sh),
            "repl": put_global_batch(repl_mat, rp_sh),
        }
        state_mat = put_global_batch(state_mat, rp_sh)
        opt = {
            "sliced": self._opt_init(params["sliced"],
                                     step_like=(S, tp, 1)),
            "repl": self._opt_init(params["repl"], step_like=(S, 1)),
        }
        for k, sh in (("sliced", sl_sh), ("repl", rp_sh)):
            if "step" in opt[k]:
                opt[k] = {**opt[k],
                          "step": put_global_batch(opt[k]["step"], sh)}
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)  # dynamic loss scale
        return TPPipeTrainState(params, state_mat, opt)

    # -- stage branch ------------------------------------------------------

    def _make_branch(self, c: int, train: bool):
        S, M, mb, A = (self.num_stages, self.num_microbatches, self.mb,
                       self._act_size)
        layers = self.model.layers[self.bounds[c]:self.bounds[c + 1]]
        in_shape = self.shapes[self.bounds[c]]
        sl_unravel, sl_len = self._sl_unravels[c], self._sl_lens[c]
        rp_unravel, rp_len = self._rp_unravels[c], self._rp_lens[c]
        st_unravel, st_len = self._st_unravels[c], self._st_lens[c]
        cdtype = self.compute_dtype
        last = c == S - 1
        tp = self.tp
        smooth = self.cfg.resolved_label_smoothing() if train else 0.0
        from ddlbench_tpu.models.moe import collect_aux_losses

        def branch(sl_row, rp_row, state_row, x_buf, xs, ys, m):
            if c == 0:
                x = lax.dynamic_index_in_dim(xs, m, keepdims=False)
            else:
                x = x_buf[: mb * math.prod(in_shape)].reshape(mb, *in_shape)
            sliced = sl_unravel(sl_row[:sl_len])
            repl = rp_unravel(rp_row[:rp_len])
            # merge the shard's sliced leaves back into each layer's dict
            # ({} sliced entry = fully replicated layer)
            params = [({**r, **s} if isinstance(s, dict) and s else r)
                      for s, r in zip(sliced, repl)]
            params = cast_params(params, cdtype)
            states = st_unravel(state_row[:st_len])
            aux: list = []
            with tensor_parallel("model", tp), collect_aux_losses(aux):
                y, new_states = apply_slice(layers, params, states,
                                            cast_input(x, cdtype), train)
            aux_mb = sum(aux, jnp.float32(0.0))
            if last:
                labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                ce = cross_entropy_loss(y, labels)
                loss = cross_entropy_loss(y, labels, smooth) if smooth else ce
                correct = correct_and_count(y, labels)[0]
                correct5 = (jnp.zeros((), jnp.int32) if train
                            else correct_topk(y, labels))
                y_out = jnp.zeros((A,), cdtype)
            else:
                loss = jnp.zeros((), jnp.float32)
                ce = jnp.zeros((), jnp.float32)
                correct = jnp.zeros((), jnp.int32)
                correct5 = jnp.zeros((), jnp.int32)
                y_out = pad_vec(y.astype(cdtype), A)
            new_state_row = pad_vec(
                ravel_pytree(new_states)[0].astype(jnp.float32),
                state_row.shape[0])
            return (_vary(y_out), _vary(new_state_row), _vary(loss),
                    _vary(ce), _vary(aux_mb), _vary(correct), _vary(correct5))

        if train and self.cfg.remat_stages:
            branch = jax.checkpoint(branch)
        return branch

    # -- compiled steps ----------------------------------------------------

    def _build_steps(self):
        self._sl_sharding = NamedSharding(self.mesh, P("stage", "model", None))
        self._rp_sharding = NamedSharding(self.mesh, P("stage", None))
        self._batch_sharding = NamedSharding(self.mesh, P(None, "data"))
        self.train_step = self._make_train_step()
        self.eval_step = self._make_eval_step()
        self._built = True

    def _make_pipe_fn(self, train: bool):
        """The V=1 fill-drain timetable (stage s runs microbatch m at tick
        t = m + s, read from partition/schedule.py's table — the runtime's
        autodiff mode, parallel/pipeline_rt.py) with TP inside every
        switch branch."""
        S, M, A = self.num_stages, self.num_microbatches, self._act_size
        aux_w = self.cfg.moe_aux_weight if train else 0.0
        branches = [self._make_branch(c, train) for c in range(S)]
        perm = [(i, i + 1) for i in range(S - 1)]
        from ddlbench_tpu.partition.schedule import fill_drain_timetable

        tt = fill_drain_timetable(S, M, 1)
        if train:
            self.timetable = tt  # --trace pipe_tick markers (gpipe parity)
        _tv, tm_np, tvalid_np = tt.forward_tick_arrays()
        t_m, t_valid = jnp.asarray(tm_np), jnp.asarray(tvalid_np)
        # Guard objective multiplier (loss scale x nan-grad poison carrier):
        # applied INSIDE the shard_map — seeding the backward with a traced
        # scalar from outside would give the cotangent an unknown
        # replication type over 'model' and fail shard_map's rep checks on
        # the TP pad/psum transposes; in-shard, the extra P() input is
        # replicated by construction. Unarmed traces take no extra arg and
        # compile the exact pre-guard program.
        guarded = train and self._guard is not None

        def inner(params, state_rows, xs, ys, *guard_args):
            # local blocks: sliced [1, 1, L_sl], repl [1, L_rp], state
            # [1, L_st], xs/ys replicated [M, mb, ...]. The pcast on the
            # replicated row transposes to its gradient psum over 'model'
            # (shared LN/bias/embedding leaves — module docstring); the
            # sliced row's gradients stay per-shard.
            sl_rows = _vary(params["sliced"][0, 0])  # [L_sl]
            rp_rows = _vary(params["repl"][0])  # [L_rp]
            state_row = _vary(state_rows[0])
            xs = _vary(xs)
            ys = _vary(ys)
            s_idx = lax.axis_index("stage")
            T = M + S - 1

            def body(carry, t):
                (x_buf, st_row, loss_acc, ce_acc, aux_acc, corr_acc,
                 corr5_acc) = carry
                valid = t_valid[t, s_idx]
                m = t_m[t, s_idx]
                (y_buf, new_st, loss_mb, ce_mb, aux_mb, corr_mb,
                 corr5_mb) = lax.switch(
                    s_idx, branches, sl_rows, rp_rows, st_row, x_buf, xs, ys,
                    m)
                st_row = jnp.where(valid, new_st, st_row)
                loss_acc = loss_acc + jnp.where(valid, loss_mb, 0.0)
                ce_acc = ce_acc + jnp.where(valid, ce_mb, 0.0)
                aux_acc = aux_acc + jnp.where(valid, aux_mb, 0.0)
                corr_acc = corr_acc + jnp.where(valid, corr_mb, 0)
                corr5_acc = corr5_acc + jnp.where(valid, corr5_mb, 0)
                if perm:
                    x_next = lax.ppermute(y_buf, "stage", perm)
                else:
                    x_next = y_buf
                return (x_next, st_row, loss_acc, ce_acc, aux_acc, corr_acc,
                        corr5_acc), None

            init_carry = (
                _vary(jnp.zeros((A,), self.compute_dtype)),
                state_row,
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.float32)),
                _vary(jnp.zeros((), jnp.int32)),
                _vary(jnp.zeros((), jnp.int32)),
            )
            (x_buf, st_row, loss_acc, ce_acc, aux_acc, corr_acc,
             corr5_acc), _ = lax.scan(body, init_carry, jnp.arange(T))
            # Loss lives on the last stage: psum over 'stage'. Every 'model'
            # shard computes the identical value (activations replicated,
            # row-parallel psums inside the blocks), so reduce over 'model'
            # with a MEAN — a sum would multiply by tp. 'data' replicas see
            # DISTINCT samples: means average over it, counts sum.
            def fold_mean(v):
                return lax.pmean(lax.pmean(lax.psum(v, "stage"), "data"),
                                 "model")

            def fold_count(v):
                return lax.pmean(lax.psum(lax.psum(v.astype(jnp.float32),
                                                   "stage"), "data"),
                                 "model").astype(jnp.int32)

            ce = fold_mean(ce_acc) / M
            aux = fold_mean(aux_acc) / M
            loss = fold_mean(loss_acc) / M + aux_w * aux
            if guarded:
                loss = loss * guard_args[0]
            correct = fold_count(corr_acc)
            correct5 = fold_count(corr5_acc)
            # Sync BN-style state across data replicas (sync-BN choice,
            # gpipe parity); 'model' shards carry identical state.
            st_row = lax.pmean(lax.pmean(st_row, "data"), "model")
            return loss, ce, st_row[None], correct, correct5

        in_specs = ({"sliced": P("stage", "model", None),
                     "repl": P("stage", None)},
                    P("stage", None), P(None, "data"), P(None, "data"))
        if guarded:
            in_specs = in_specs + (P(),)
        return _shard_map(
            inner,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=(P(), P(), P("stage", None), P(), P()),
        )

    @property
    def _total_samples(self) -> int:
        return self.num_microbatches * self.mb * self.dp

    def _ts_sharding(self):
        params_sh = {"sliced": self._sl_sharding, "repl": self._rp_sharding}
        from ddlbench_tpu.parallel.common import opt_state_sharding

        opt_sh = {
            "sliced": opt_state_sharding(self.cfg, self._sl_sharding,
                                         self._sl_sharding),
            "repl": opt_state_sharding(self.cfg, self._rp_sharding,
                                       self._rp_sharding),
        }
        if self._guard is not None:
            opt_sh = self._guard.opt_state_spec(
                opt_sh, NamedSharding(self.mesh, P()))
        return TPPipeTrainState(params_sh, self._rp_sharding, opt_sh)

    def _make_train_step(self):
        pipe_train = self._make_pipe_fn(train=True)
        guard = self._guard

        def train_step(ts: TPPipeTrainState, xs, ys, lr):
            gstate, smul, opt_in = None, None, ts.opt
            if guard is not None:
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)

            def loss_fn(params):
                # smul rides into the shard_map as a replicated input (see
                # _make_pipe_fn): the objective scaling must happen
                # in-shard for the 'model'-axis transposes to typecheck
                args = (smul,) if smul is not None else ()
                loss, ce, new_state, correct, _c5 = pipe_train(
                    params, ts.model_state, xs, ys, *args)
                return loss, (ce, new_state, correct)

            (_, (ce, new_state, correct)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(ts.params)
            if guard is not None:
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
            new_sl, opt_sl = self._opt_update(
                ts.params["sliced"], grads["sliced"], opt_in["sliced"], lr)
            new_rp, opt_rp = self._opt_update(
                ts.params["repl"], grads["repl"], opt_in["repl"], lr)
            new_params = {"sliced": new_sl, "repl": new_rp}
            new_opt = {"sliced": opt_sl, "repl": opt_rp}
            gm = None
            if guard is not None:
                new_params, new_state, new_opt, gm = guard.commit(
                    finite, gnorm, gstate, (new_params, new_state, new_opt),
                    (ts.params, ts.model_state, opt_in))
            valid = jnp.sum((ys >= 0).astype(jnp.float32))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid),
            }
            if gm is not None:
                metrics.update(gm)
            return TPPipeTrainState(new_params, new_state, new_opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding, None),
        )

    def _make_eval_step(self):
        pipe_eval = self._make_pipe_fn(train=False)

        def eval_step(ts, xs, ys):
            loss, _, _, correct, correct5 = pipe_eval(
                ts.params, ts.model_state, xs, ys)
            return {
                "loss": loss,
                "correct": correct,
                "correct5": correct5,
                "count": jnp.sum((ys >= 0).astype(jnp.int32)),
            }

        return jax.jit(
            eval_step,
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding),
        )

    # -- data placement ----------------------------------------------------

    def shard_batch(self, x, y):
        """Global batch [M*mb*dp, ...] -> [M, dp*mb, ...] sharded over
        'data' (TP shards features; each data replica runs mb rows of every
        microbatch — gpipe convention)."""
        from ddlbench_tpu.distributed import put_global_batch

        M, mb, dp = self.num_microbatches, self.mb, self.dp
        x = x.reshape(M, dp * mb, *x.shape[1:])
        y = y.reshape(M, dp * mb, *y.shape[1:])
        return (put_global_batch(x, self._batch_sharding),
                put_global_batch(y, self._batch_sharding))

    @property
    def world_size(self) -> int:
        return self.mesh.devices.size
