"""Stage packing: heterogeneous per-stage pytrees as one sharded matrix.

TPU pipeline parallelism wants SPMD: every device runs the same program over a
mesh 'stage' axis, with `lax.switch(stage_index, ...)` selecting that device's
stage computation. But a CNN's stages have *heterogeneous* parameter pytrees
(different conv shapes per stage), which cannot be stacked into one
mesh-shardable array directly.

The trick: flatten each stage's pytree to a single f32 vector
(`jax.flatten_util.ravel_pytree`), right-pad every vector to the longest one,
and stack into a ``[num_stages, max_len]`` matrix sharded ``P('stage')`` — each
device holds exactly its own stage's parameters (plus padding). Each switch
branch closes over its stage's ``unravel`` to reconstruct the pytree from its
row. SGD/momentum updates apply elementwise to the packed matrix, so the
optimizer is stage-agnostic, and weight-version stashing (PipeDream) is just a
leading axis on the same matrix.

Activations crossing stage boundaries get the same treatment: padded flat
vectors of the largest boundary, so `lax.ppermute` moves one fixed-shape buffer
between neighbors.

This replaces the reference's per-stage generated Python modules
(pipedream-fork/optimizer/convert_graph_to_model.py:224-329): partition = data,
not source code.
"""

from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def pack_stage(tree: Any) -> Tuple[jax.Array, Callable[[jax.Array], Any], int]:
    """Flatten one stage's pytree. Returns (vec_f32, unravel, true_len)."""
    vec, unravel = ravel_pytree(tree)
    if vec.size == 0:
        vec = jnp.zeros((1,), jnp.float32)
        empty_unravel = unravel

        def unravel_empty(v, _u=empty_unravel):
            return _u(v[:0])

        return vec.astype(jnp.float32), unravel_empty, 0
    return vec.astype(jnp.float32), unravel, int(vec.size)


def pack_stages(stage_trees: Sequence[Any]):
    """Pack a list of per-stage pytrees into ([S, L] matrix, unravels, lens).

    ``unravels[s]`` maps a length-``lens[s]`` prefix of row ``s`` back to the
    stage's pytree.
    """
    vecs, unravels, lens = [], [], []
    for tree in stage_trees:
        v, u, n = pack_stage(tree)
        vecs.append(v)
        unravels.append(u)
        lens.append(n)
    max_len = max(max(lens), 1)
    mat = jnp.stack([jnp.pad(v, (0, max_len - v.size)) for v in vecs])
    return mat, unravels, lens


def unpack_row(row: jax.Array, unravel: Callable, true_len: int) -> Any:
    return unravel(row[:true_len]) if true_len else unravel(row)


def pad_vec(vec: jax.Array, size: int) -> jax.Array:
    return jnp.pad(vec.reshape(-1), (0, size - vec.size))


def balanced_stage_bounds(costs: Sequence[float], num_stages: int) -> List[int]:
    """Split a chain of per-layer costs into contiguous stages minimizing the
    max stage cost (the load-balance objective of torchgpipe's balance_by_time,
    benchmark/mnist/mnist_gpipe.py:215-217). Exact DP; n is small.

    Returns bounds of length num_stages+1 with bounds[0]=0, bounds[-1]=n.
    """
    n = len(costs)
    if num_stages >= n:
        # degenerate: one layer per stage, pad trailing bounds
        return list(range(n + 1)) + [n] * (num_stages - n)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + float(c))

    def span(i, j):  # cost of layers [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # dp[k][j] = min over splits of max-load using k stages for first j layers
    dp = [[INF] * (n + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (n + 1) for _ in range(num_stages + 1)]
    dp[0][0] = 0.0
    for k in range(1, num_stages + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                v = max(dp[k - 1][i], span(i, j))
                if v < dp[k][j]:
                    dp[k][j] = v
                    cut[k][j] = i
    bounds = [n]
    j = n
    for k in range(num_stages, 0, -1):
        j = cut[k][j]
        bounds.append(j)
    return bounds[::-1]


def layer_flop_costs(params_list: Sequence[Any],
                     shapes: Sequence[Tuple[int, ...]],
                     layers: Optional[Sequence[Any]] = None) -> List[float]:
    """Analytic per-layer FLOP estimate for load balancing.

    For convolutions FLOPs = 2 * n_params * out_H * out_W (exact for dense
    layers with spatial=1), which is what dominates these CNNs. ``shapes``
    are the per-example boundary shapes from init_model. A layer whose
    output shape hides its compute geometry overrides the spatial factor
    via Layer.cost_spatial (packed composite spans emit flat boundaries
    that would otherwise read as spatial=1) — pass ``layers`` to honor it.
    """
    costs = []
    for i, (p, out_shape) in enumerate(zip(params_list, shapes[1:])):
        spatial = None
        if layers is not None:
            spatial = getattr(layers[i], "cost_spatial", None)
        if isinstance(spatial, (list, tuple)):
            # multi-node packed span: its params are the span's per-node
            # list, so the exact per-node sum is available — a max would
            # over-weight spans mixing large-spatial convs with dense
            # nodes (ADVICE r3)
            if isinstance(p, (list, tuple)) and len(p) == len(spatial):
                costs.append(sum(
                    max(1.0, 2.0 * sum(int(x.size)
                                       for x in jax.tree.leaves(pn)) * s)
                    for pn, s in zip(p, spatial)))
                continue
            spatial = max(spatial)  # params shape unknown: upper bound
        n_params = sum(int(x.size) for x in jax.tree.leaves(p))
        if spatial is None:
            spatial = math.prod(out_shape[:-1]) if len(out_shape) > 1 else 1
        costs.append(max(1.0, 2.0 * n_params * spatial))
    return costs
