"""Strategy factory: one entry point for the four parallelization engines.

The reference binds workloads to engines by having nine separate driver
scripts (SURVEY.md §1 L4); here ``make_strategy(cfg)`` returns an object with
a uniform interface consumed by one train loop (ddlbench_tpu/train/loop.py):

* ``init(key) -> train_state`` (device-placed/sharded)
* ``shard_batch(x, y) -> batch_args`` — place a global batch onto the
  strategy's mesh. The result is an OPAQUE tuple of the data arguments the
  step functions expect; callers always splat it
  (``train_step(ts, *batch_args, lr)``). Most strategies return (x, y); the
  hetero engines return per-device row shards plus a per-microbatch
  valid-count vector. CONTRACT: ``shard_batch`` must be callable off the
  main thread — the async input pipeline (data/prefetch.py) runs it on a
  producer thread so device placement overlaps compute. Implementations
  must therefore be pure placement (device_put / reshape of their
  arguments + immutable self state), never mutate per-call host state, and
  never assume main-thread-only facilities (signal handlers, thread-local
  tracing contexts).
* ``train_step(train_state, *batch_args, lr) -> (train_state, metrics)``
  (jitted)
* ``eval_step(train_state, *batch_args) -> {loss, correct, count[,
  correct5]}`` (jitted; ``correct5`` is the optional prec@5 numerator — the
  loop reports top5 only when a strategy provides it)
* ``world_size``
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

import jax

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.zoo import get_model



# Persisted auto-partition plan (reference parity: the optimizer's output
# outlives the process as gpus=N.txt + generated stage code,
# optimizer_graph_hierarchical.py:334-346 / run_template.sh:436-498). Here
# the plan is data: the graph-level stage bounds plus the cfg fields the
# plan rewrote. Persisting it next to the checkpoints makes --resume
# independent of profiling noise — a time-mode re-profile could otherwise
# pick different bounds and fail the restore on shape mismatch.
_PLAN_FILE = "partition.json"


def _plan_path(cfg: RunConfig):
    return (os.path.join(cfg.checkpoint_dir, _PLAN_FILE)
            if cfg.checkpoint_dir else None)


def _load_plan(cfg: RunConfig, key: dict):
    """Returns (plan_or_None, keep_existing): ``keep_existing`` marks a
    readable plan whose key mismatched — it belongs to a DIFFERENT run
    configuration (possibly a flag typo) and must not be overwritten by
    this run's re-profile."""
    path = _plan_path(cfg)
    if not (cfg.resume and path and os.path.exists(path)):
        return None, False
    try:
        with open(path) as f:
            plan = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        print(f"auto-partition: ignoring unreadable plan {path} ({e}); "
              f"re-profiling", flush=True)
        return None, False
    pkey = plan.get("key")
    if _stale_pre_plan_key(pkey, key):
        # migration shim: a stale pre-plan-mode partition.json (written
        # before _plan_key carried the "plan" field) that otherwise
        # matches this run must invalidate LOUDLY and re-solve — never
        # KeyError on the missing field, and never count as a foreign
        # config (keep_existing stays False so the re-solve overwrites it)
        print(f"auto-partition: persisted plan {path} predates the "
              f"--plan mode field; invalidating (re-profiling and "
              f"re-writing)", flush=True)
        return None, False
    if pkey != key:
        print(f"auto-partition: persisted plan {path} was computed for "
              f"{plan.get('key')}, run is {key}; re-profiling (the "
              f"existing plan file is kept)", flush=True)
        return None, True
    if plan.get("fingerprint") != _plan_fingerprint(cfg):
        # same run identity but the COST MODEL changed (--hbm-gb /
        # --profile-mode): the persisted bounds were solved under other
        # feasibility gates — re-profile in place (missing fingerprint =
        # a pre-fingerprint file, invalidated the same way)
        print(f"auto-partition: persisted plan {path} was solved under a "
              f"different cost model ({plan.get('fingerprint')}); "
              f"re-profiling and re-writing", flush=True)
        return None, False
    return plan, False


def _plan_key(cfg: RunConfig) -> dict:
    """The fields a persisted plan must match to be reusable: a plan from a
    different model/topology would mis-shard or trip shape asserts, and one
    from different batch/virtual-stage flags would silently override what
    the user asked for. ``pipe_schedule`` and the cost-model mode are part
    of the key too — a plan solved (and whose cost vectors were extracted)
    under one schedule/cost model must never be silently reused by another
    run's timetable. Must be computed from the PRE-rewrite cfg (plans
    rewrite micro_batch_size etc.), so callers capture it up front."""
    mb, chunks = cfg.resolved_batches()
    return {"arch": cfg.arch, "benchmark": cfg.benchmark,
            "strategy": cfg.strategy, "num_devices": cfg.num_devices,
            "num_hosts": cfg.num_hosts, "micro_batch_size": mb,
            "num_microbatches": chunks, "virtual_stages": cfg.virtual_stages,
            "pipe_schedule": cfg.pipe_schedule,
            "pipe_costs": cfg.pipe_costs,
            # the plan MODE is part of the identity: an --auto-partition
            # bounds plan and a --plan auto full-mix plan live in the same
            # file but mean different things (pre-plan-mode files are
            # invalidated loudly by the migration shim in _load_plan /
            # planner._load_cached, never KeyError'd)
            "plan": cfg.plan}


def _plan_fingerprint(cfg: RunConfig) -> dict:
    """The cost-model half of a persisted plan's identity: the key names
    WHAT was planned (model, topology, batch grammar, plan mode); a plan
    additionally depends on HOW costs and feasibility were priced, so the
    fingerprint pins the profile mode and the hardware constants
    (--hbm-gb rides cfg.hardware). Shared by the --auto-partition bounds
    plan here and the --plan auto record (partition/planner.py)."""
    import dataclasses

    return {"profile_mode": cfg.profile_mode,
            "hardware": dataclasses.asdict(cfg.hardware)}


def _stale_pre_plan_key(old_key, key: dict) -> bool:
    """The migration shim's ONE match rule: ``old_key`` predates the
    plan-mode field (no "plan" entry) but otherwise names exactly this
    run's configuration — whatever mode is now looking at it. Shared by
    the loader and writer here; planner._load_cached deliberately uses a
    BROADER rule (any pre-plan-mode file invalidates a --plan auto read,
    matching or not, since the old schema carries no plan_auto record)."""
    return (isinstance(old_key, dict) and "plan" not in old_key
            and {**old_key, "plan": key.get("plan")} == key)


def _backup_foreign_plan(path: str, key: dict) -> None:
    """A fresh (non-resume) run pointed at a checkpoint_dir holding a
    DIFFERENT configuration's plan — e.g. a flag typo — must not silently
    clobber it next to that run's checkpoints (ADVICE r3): keep a backup.
    Shared by the --auto-partition bounds writer below and the --plan auto
    full-mix writer (partition/planner.py). A stale pre-plan-mode file of
    the SAME configuration is not foreign — the migration shim already
    invalidated it, so the re-solve overwrites in place."""
    if not os.path.exists(path):
        return
    try:
        with open(path) as f:
            old_key = json.load(f).get("key")
    except (json.JSONDecodeError, OSError):
        old_key = None
    if _stale_pre_plan_key(old_key, key):
        # pre-plan-mode file of this very config (whichever mode is now
        # re-solving it): the migration shim already invalidated it
        # loudly, so the re-solve overwrites in place
        return
    if old_key != key:
        bak = path + ".bak"
        n = 1
        while os.path.exists(bak):  # never clobber an earlier backup
            bak = f"{path}.bak{n}"
            n += 1
        os.replace(path, bak)
        print(f"auto-partition: existing plan {path} belongs to a "
              f"different configuration ({old_key}); backed up to {bak}",
              flush=True)


def _save_plan(key: dict, cfg: RunConfig, graph_bounds) -> None:
    path = _plan_path(cfg)
    if path is None:
        return
    os.makedirs(cfg.checkpoint_dir, exist_ok=True)
    _backup_foreign_plan(path, key)
    repl = cfg.stage_replication
    payload = {
        "key": key,
        "fingerprint": _plan_fingerprint(cfg),
        "graph_bounds": [int(b) for b in graph_bounds],
        "num_stages": cfg.num_stages,
        "dp_replicas": cfg.dp_replicas,
        "stage_replication": list(repl) if repl else None,
        "micro_batch_size": cfg.micro_batch_size,
        "num_microbatches": cfg.num_microbatches,
        "virtual_stages": cfg.virtual_stages,
        # schedule/cost provenance: which timetable and cost model the
        # plan was solved under, plus the resolved per-chunk (f, b, w)
        # half-tick vectors so a --resume reuses the exact weighted
        # timetable without re-profiling
        "pipe_schedule": cfg.pipe_schedule,
        "pipe_costs": cfg.pipe_costs,
        "pipe_cost_vectors": ([list(v) for v in cfg.pipe_cost_vectors]
                              if cfg.pipe_cost_vectors else None),
    }
    # atomic: the window-catching harness SIGKILLs overdue runs, and a
    # truncated plan file would break every later --resume
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _measured_bubbles(cfg: RunConfig):
    """{schedule: measured bubble fraction} reduced from the trace JSON a
    prior run left under ``--trace`` (``--schedule-trace PATH``), via the
    telemetry/bubble.py reducer — the advisor then ranks that schedule by
    what it actually did on this machine instead of the analytic model.
    None (advice stays analytic) when no trace is supplied, it is
    unreadable, or it carries no pipe_tick projections."""
    if not cfg.schedule_trace:
        return None
    from ddlbench_tpu.telemetry.bubble import bubble_fraction

    try:
        with open(cfg.schedule_trace) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"schedule advisor: unreadable --schedule-trace "
              f"{cfg.schedule_trace} ({e}); using analytic bubbles",
              flush=True)
        return None
    got = bubble_fraction(doc)
    if not got["tick_spans"] or not got.get("schedule"):
        print(f"schedule advisor: {cfg.schedule_trace} carries no "
              f"pipe_tick projections; using analytic bubbles", flush=True)
        return None
    print(f"schedule advisor: measured bubble "
          f"{got['bubble_fraction']:.4f} for {got['schedule']} "
          f"({got['tick_spans']} tick spans, {got['stages']} stages, "
          f"{cfg.schedule_trace})", flush=True)
    return {got["schedule"]: got["bubble_fraction"]}


def make_strategy(cfg: RunConfig, devices: Optional[Sequence[jax.Device]] = None,
                  input_time_ms: float = 0.0):
    """Build the configured strategy. ``input_time_ms``: measured
    per-MICROBATCH data-loading cost (profiler.measure_input_ms scaled by
    the caller) — with --auto-partition it becomes the profile graph's Input
    node, folded into layer 0's stage for the partitioning DP
    (profiler.fold_input_node; train/loop.py supplies it for the -s path)."""
    if cfg.plan == "auto":
        # normally already resolved at run start (train/loop.py), where the
        # rewritten strategy also shapes the data stream and lr scaling;
        # direct callers (tools, tests) get the same rewrite here
        from ddlbench_tpu.partition.planner import resolve_auto_plan

        cfg = resolve_auto_plan(cfg, input_time_ms=input_time_ms)
    cfg.validate()
    from ddlbench_tpu.models.transformer import set_attention_backend

    set_attention_backend(cfg.attention_backend)
    model = get_model(cfg.arch, cfg.benchmark,
                      moe_capacity_factor=cfg.moe_capacity_factor)

    stage_bounds = None
    if cfg.auto_partition and cfg.strategy in ("gpipe", "pipedream"):
        # profile -> partition -> EXECUTE the plan: the reference's PipeDream
        # phases 1-3 (profiler main.py -> optimizer_graph_hierarchical.py ->
        # convert_graph_to_model.py), whose output actually configures its
        # runtime (run_template.sh:436-498). The plan's stage bounds and
        # per-stage replication factors drive the mesh: uniform plans run on
        # the 2-D ('data','stage') mesh, uneven plans on parallel/hetero.py's
        # flat 'pipe' axis.
        from ddlbench_tpu.partition.optimizer import (
            partition_hierarchical,
            stage_bounds_from_graph,
        )
        from ddlbench_tpu.profiler.profile import profile_model

        mb, chunks = cfg.resolved_batches()
        from ddlbench_tpu.models.branchy import get_dag

        spec = cfg.dataset()
        dag = get_dag(cfg.arch, spec.image_size, spec.num_classes)
        dag_shapes = None
        plan_key = _plan_key(cfg)  # pre-rewrite flags; plans rewrite cfg
        persisted, keep_existing = _load_plan(cfg, plan_key)
        applied = False
        if persisted is not None:
            cfg_before = cfg
            try:
                stage_bounds = [int(b) for b in persisted["graph_bounds"]]
                repl_p = persisted.get("stage_replication")
                cv_p = persisted.get("pipe_cost_vectors")
                cfg = cfg.replace(
                    num_stages=persisted["num_stages"],
                    dp_replicas=persisted["dp_replicas"],
                    stage_replication=tuple(repl_p) if repl_p else None,
                    micro_batch_size=persisted["micro_batch_size"],
                    num_microbatches=persisted["num_microbatches"],
                    virtual_stages=persisted.get("virtual_stages", 1),
                    pipe_cost_vectors=(tuple(tuple(int(x) for x in v)
                                             for v in cv_p)
                                       if cv_p else None))
                cfg.validate()
                applied = True
                print(f"auto-partition: reusing persisted plan "
                      f"({_plan_path(cfg)}, bounds={stage_bounds})",
                      flush=True)
            except (KeyError, TypeError, ValueError) as e:
                # schema drift / hand edit / no-longer-valid combination:
                # fall back to re-profiling, same as no plan at all
                cfg = cfg_before
                print(f"auto-partition: persisted plan not applicable "
                      f"({e!r}); re-profiling", flush=True)
        if not applied:
            if dag is not None:
                # branchy arch: profile the REAL dataflow DAG (the reference
                # traces these with TensorWrapper, graph_creator.py:55-195),
                # then chainize it at NODE granularity with packed-crossing
                # boundary sizes — the partitioner may cut at any position
                # (incl. non-articulation cuts where several tensors cross,
                # e.g. between nasnet cells) and the chosen cuts are executed
                # via branchy.to_packed_chain below
                from ddlbench_tpu.profiler.profile import (packed_chain_graph,
                                                           profile_dag)

                cdtype = jax.numpy.dtype(cfg.compute_dtype)
                dag_graph, dag_shapes = profile_dag(
                    dag, mb, mode=cfg.profile_mode, dtype=cdtype,
                    hw=cfg.hardware, return_shapes=True)
                # one itemsize everywhere: the profile's activation sizes and
                # the input-crossing bytes below must share units for the DP's
                # cut comparison to be meaningful
                graph = packed_chain_graph(dag_graph, dag, mb,
                                           itemsize=cdtype.itemsize)
                if input_time_ms > 0.0:
                    # fold_input_node semantics: data loading prices into the
                    # stage hosting block 0
                    graph.topological_sort()[0].forward_compute_time += (
                        input_time_ms)
            else:
                graph = profile_model(model, mb, mode=cfg.profile_mode,
                                      hw=cfg.hardware,
                                      input_time_ms=input_time_ms)
                # DP view: the Input node folds into layer 0's stage — the
                # reference co-locates its DataLoader with stage 0's ranks, and
                # a chip cannot run "just data loading", so Input must never
                # form its own stage.
                from ddlbench_tpu.profiler.profile import fold_input_node

                graph = fold_input_node(graph)

            if cfg.virtual_stages > 1:
                # interleaved runtimes live on the 2-D grid, whose plans are
                # uniform by construction — search ONLY that executable family
                # (partition_interleaved) and execute the winner, rather than
                # emitting a hetero plan the V>1 runtime would have to drop
                from ddlbench_tpu.partition.optimizer import partition_interleaved

                iplan = partition_interleaved(
                    graph, cfg.num_devices, cfg.virtual_stages, cfg.hardware,
                    num_hosts=cfg.num_hosts, num_microbatches=chunks,
                    micro_batch=mb)
                stage_bounds = list(iplan.bounds)
                # replicas split each microbatch's rows — the caller's global
                # batch M*mb is unchanged (same convention as the uniform-plan
                # rewrite below)
                cfg = cfg.replace(
                    num_stages=iplan.num_stages, dp_replicas=iplan.replication,
                    stage_replication=None,
                    micro_batch_size=mb // iplan.replication,
                    num_microbatches=chunks)
                print(
                    f"auto-partition (interleaved): executing "
                    f"S={iplan.num_stages} x V={iplan.virtual_stages} "
                    f"(replication={iplan.replication}, bounds={stage_bounds}, "
                    f"bottleneck {iplan.pipeline_time_ms:.3f} ms)",
                    flush=True,
                )
                plan = None
            else:
                plan = partition_hierarchical(
                    graph, cfg.num_devices, cfg.hardware, num_hosts=cfg.num_hosts
                )
                repl = tuple(s.replication for s in plan.stages)
            if plan is not None:
                if repl and len(set(repl)) == 1 and mb % repl[0] == 0:
                    # uniform plan: normalize straight to the 2-D-mesh
                    # form (the same rewrite the strategy dispatch below
                    # applies) so event schedules / the hybrid engine —
                    # which reject hetero stage_replication tuples — can
                    # still execute the plan's bounds instead of falling
                    # back to balanced ones
                    cfg_planned = cfg.replace(
                        num_stages=len(repl), dp_replicas=repl[0],
                        stage_replication=None,
                        micro_batch_size=mb // repl[0],
                        num_microbatches=chunks)
                else:
                    cfg_planned = cfg.replace(
                        num_stages=None, dp_replicas=1,
                        stage_replication=repl)
                try:
                    cfg_planned.validate()
                    stage_bounds = plan.stage_bounds()
                    cfg = cfg_planned
                    print(
                        f"auto-partition: executing plan "
                        f"{[(s.start, s.end, s.replication) for s in plan.stages]} "
                        f"(bounds={stage_bounds}, replication={repl}, "
                        f"bottleneck {plan.pipeline_time_ms:.3f} ms)",
                        flush=True,
                    )
                except ValueError as e:
                    # e.g. micro-batch not divisible by a replication factor:
                    # keep the profiled balanced split rather than fail the run
                    stage_bounds = stage_bounds_from_graph(
                        graph, cfg.resolved_stages())
                    print(
                        f"auto-partition: plan {repl} not executable ({e}); "
                        f"falling back to balanced bounds {stage_bounds}",
                        flush=True,
                    )
            if cfg.pipe_costs == "profile":
                # cost-weighted timetables: sum the profile graph's
                # per-node times over the CHOSEN chunk bounds and
                # quantize onto the half-tick grid — the event runtime
                # then executes a table packed for the plan's genuinely
                # uneven chunks instead of the F=B=W unit fiction
                from ddlbench_tpu.partition.schedule import (
                    quantize_cost_vectors_clipped)
                from ddlbench_tpu.profiler.profile import chunk_cost_ms

                f_ms, b_ms = chunk_cost_ms(graph, stage_bounds)
                # the searched packer needs to SEE the real unevenness:
                # an 8-half-tick cap flattens extreme profiles into the
                # same grid the heuristics already pack (no-silent-caps)
                max_units = 64 if cfg.pipe_schedule == "searched" else 8
                vectors, clipped = quantize_cost_vectors_clipped(
                    f_ms, b_ms, max_units=max_units)
                cfg = cfg.replace(pipe_cost_vectors=vectors)
                print(f"auto-partition: cost-weighted timetable vectors "
                      f"(f/b/w half-ticks per chunk) {vectors}", flush=True)
                if clipped:
                    print(f"auto-partition: WARNING {clipped} event cost(s) "
                          f"clipped at the {max_units}-half-tick "
                          f"quantization cap — the timetable underweights "
                          f"the most expensive chunks (profile is more "
                          f"uneven than the grid can express)", flush=True)
            if not keep_existing:
                _save_plan(plan_key, cfg, stage_bounds)
        if dag is not None:
            # execute the chosen node-position cuts: one packed composite
            # span per chunk, boundaries carry every crossing tensor in one
            # flat buffer (branchy.to_packed_chain docstring)
            from ddlbench_tpu.models.branchy import to_packed_chain

            model = to_packed_chain(dag, stage_bounds[1:-1],
                                    out_shapes=dag_shapes)
            stage_bounds = list(range(len(model.layers) + 1))
            print(f"auto-partition: packed-boundary chain, "
                  f"{len(model.layers)} spans", flush=True)
        if cfg.strategy == "gpipe":
            from ddlbench_tpu.partition.schedule import (
                recommend_schedule, recommend_virtual_stages)

            _, chunks = cfg.resolved_batches()
            table = recommend_virtual_stages(
                cfg.resolved_stages(), chunks, len(model.layers))
            print(f"schedule advisor (S={cfg.resolved_stages()}, M={chunks}): "
                  f"{table}", flush=True)
            # schedules are data now: advise the best TIMETABLE at the
            # chosen V, not just the best V — ranked by the cost-weighted
            # bubble when the plan carries cost vectors, and by the
            # MEASURED bubble for any schedule a --schedule-trace covers
            # (reality outranks the model, ROADMAP item 2c)
            measured = _measured_bubbles(cfg)
            sched = recommend_schedule(cfg.resolved_stages(), chunks,
                                       cfg.virtual_stages,
                                       costs=cfg.pipe_cost_vectors,
                                       measured=measured)
            best = sched[0]
            tail = ("" if best["schedule"] == cfg.pipe_schedule else
                    f" (run has --pipe-schedule {cfg.pipe_schedule})")
            basis = ("measured" if "bubble_measured" in best
                     else "weighted" if cfg.pipe_cost_vectors else "analytic")
            print(f"schedule advisor: best schedule at V="
                  f"{cfg.virtual_stages} is {best['schedule']} "
                  f"({basis} bubble "
                  f"{best.get('bubble_measured', best['bubble'])})"
                  f"{tail}: {sched}", flush=True)
    if stage_bounds is None and cfg.plan_bounds is not None and \
            cfg.strategy in ("gpipe", "pipedream"):
        # Explicit stage bounds (--plan-bounds, or a solved --plan auto
        # rewrite): the engine executes exactly this split instead of its
        # balanced default — the end of the profile -> graph -> plan loop.
        # config.validate could not know the layer count; check it here
        # (a named error, not the engine's bare assert)
        if cfg.plan_bounds[-1] != len(model.layers):
            raise ValueError(
                f"--plan-bounds {list(cfg.plan_bounds)} must end at the "
                f"model's layer count ({cfg.arch} has "
                f"{len(model.layers)} layers)")
        stage_bounds = [int(b) for b in cfg.plan_bounds]
    if (stage_bounds is None and cfg.strategy in ("gpipe", "pipedream")):
        # Manual (non-auto-partition) pipeline run on a branchy arch: the
        # articulation chain is hopeless to balance (nasnet's whole cell
        # stack is ONE block — two tensors cross every cell boundary), so
        # split at NODE granularity over packed boundaries instead; the
        # engines' balanced default split then has n positions to choose
        # from, like any chain model.
        from ddlbench_tpu.models.branchy import get_dag, to_packed_chain

        spec_b = cfg.dataset()
        dag_b = get_dag(cfg.arch, spec_b.image_size, spec_b.num_classes)
        if dag_b is not None:
            model = to_packed_chain(
                dag_b, range(1, len(dag_b.layers)))
            print(f"branchy arch: node-granular packed chain "
                  f"({len(model.layers)} layers) for the stage split",
                  flush=True)
    if cfg.strategy == "single":
        from ddlbench_tpu.parallel.single import SingleStrategy

        return SingleStrategy(model, cfg)
    if cfg.strategy == "dp":
        from ddlbench_tpu.parallel.dp import DPStrategy, make_data_mesh

        mesh = make_data_mesh(cfg.num_devices, devices)
        return DPStrategy(model, cfg, mesh)
    repl = tuple(cfg.stage_replication or ())
    if repl and len(set(repl)) == 1:
        # Uniform plan: the regular 2-D ('data','stage') mesh executes it
        # (cheaper than the flat-axis conveyor). stage_replication semantics
        # are "replicas split each microbatch's rows", so the per-replica
        # micro-batch becomes mb/r — the global batch stays M*mb, matching
        # cfg.global_batch()'s stage_replication accounting for the caller.
        mb_, chunks_ = cfg.resolved_batches()
        cfg = cfg.replace(stage_replication=None, dp_replicas=repl[0],
                          num_stages=len(repl),
                          micro_batch_size=mb_ // repl[0],
                          num_microbatches=chunks_)
        repl = ()
    if cfg.strategy == "gpipe":
        if repl:
            from ddlbench_tpu.parallel.hetero import HeteroGPipeStrategy

            return HeteroGPipeStrategy(model, cfg, devices=devices,
                                       stage_bounds=stage_bounds)
        if cfg.tp_size > 1:
            from ddlbench_tpu.parallel.tpp import TPGPipeStrategy

            return TPGPipeStrategy(model, cfg, devices=devices,
                                   stage_bounds=stage_bounds)
        if cfg.pipe_schedule != "fill-drain":
            # schedule-programmable runtime: 1f1b / interleaved /
            # zero-bubble are TIMETABLES compiled by one event-mode engine
            # (parallel/pipeline_rt.py), not engines of their own
            from ddlbench_tpu.parallel.pipeline_rt import (
                ScheduledPipelineStrategy)

            return ScheduledPipelineStrategy(model, cfg, devices=devices,
                                             stage_bounds=stage_bounds)
        from ddlbench_tpu.parallel.gpipe import GPipeStrategy

        return GPipeStrategy(model, cfg, devices=devices, stage_bounds=stage_bounds)
    if cfg.strategy == "pipedream":
        if repl:
            from ddlbench_tpu.parallel.hetero import HeteroPipeDreamStrategy

            return HeteroPipeDreamStrategy(model, cfg, devices=devices,
                                           stage_bounds=stage_bounds)
        from ddlbench_tpu.parallel.pipedream import PipeDreamStrategy

        return PipeDreamStrategy(model, cfg, devices=devices, stage_bounds=stage_bounds)
    if cfg.strategy == "sp":
        from ddlbench_tpu.parallel.sp import SPStrategy

        return SPStrategy(model, cfg, devices=devices)
    if cfg.strategy == "tp":
        from ddlbench_tpu.parallel.sharded import TPStrategy

        return TPStrategy(model, cfg, devices=devices)
    if cfg.strategy == "fsdp":
        from ddlbench_tpu.parallel.sharded import FSDPStrategy

        return FSDPStrategy(model, cfg, devices=devices)
    if cfg.strategy == "ep":
        from ddlbench_tpu.parallel.ep import EPStrategy

        return EPStrategy(model, cfg, devices=devices)
    raise ValueError(cfg.strategy)
