"""Strategy factory: one entry point for the four parallelization engines.

The reference binds workloads to engines by having nine separate driver
scripts (SURVEY.md §1 L4); here ``make_strategy(cfg)`` returns an object with
a uniform interface consumed by one train loop (ddlbench_tpu/train/loop.py):

* ``init(key) -> train_state`` (device-placed/sharded)
* ``train_step(train_state, x, y, lr) -> (train_state, metrics)`` (jitted)
* ``eval_step(train_state, x, y) -> {loss, correct, count}`` (jitted)
* ``shard_batch(x, y)`` — place a global batch onto the strategy's mesh
* ``world_size``
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.zoo import get_model


def make_strategy(cfg: RunConfig, devices: Optional[Sequence[jax.Device]] = None):
    cfg.validate()
    model = get_model(cfg.arch, cfg.benchmark)
    if cfg.strategy == "single":
        from ddlbench_tpu.parallel.single import SingleStrategy

        return SingleStrategy(model, cfg)
    if cfg.strategy == "dp":
        from ddlbench_tpu.parallel.dp import DPStrategy, make_data_mesh

        mesh = make_data_mesh(cfg.num_devices, devices)
        return DPStrategy(model, cfg, mesh)
    if cfg.strategy == "gpipe":
        from ddlbench_tpu.parallel.gpipe import GPipeStrategy

        return GPipeStrategy(model, cfg, devices=devices)
    if cfg.strategy == "pipedream":
        from ddlbench_tpu.parallel.pipedream import PipeDreamStrategy

        return PipeDreamStrategy(model, cfg, devices=devices)
    if cfg.strategy == "sp":
        from ddlbench_tpu.parallel.sp import SPStrategy

        return SPStrategy(model, cfg, devices=devices)
    raise ValueError(cfg.strategy)
