"""Programmable pipeline-schedule runtime: timetables in, one engine out.

ROADMAP item 2 / Piper's thesis (PAPERS.md): a pipeline SCHEDULE should be
a description consumed by one runtime, not an engine. partition/schedule.py
ships a FAMILY of timetables as data — fill-drain (GPipe), synchronous
1F1B, interleaved-1F1B, zero-bubble (ZB-H1-style split backward),
zero-bubble-h2 (lifted in-flight cap + boundary-deferred W) and searched
tables (partition/schedule_search.py's budgeted local search) — and this
module compiles any of them to the one-XLA-program scan+ppermute machinery
the legacy gpipe/pipedream engines each reimplemented:

* **autodiff mode** (fill-drain, any V): the timetable's forward phase
  drives a `lax.scan` over T = M*V + S - 1 ticks (`lax.switch` per tick,
  ring ppermute handoffs); `jax.grad` through the scan realizes the
  table's backward half automatically (ppermute transposes to the reverse
  permutation, jax.checkpoint per stage = recompute). parallel/gpipe.py
  and parallel/tpp.py consume the table through
  :func:`Timetable.forward_tick_arrays` — the closed-form index math they
  used to inline now lives in the schedule description.
* **event mode** (1f1b / interleaved / zero-bubble —
  :class:`ScheduledPipelineStrategy`): a `lax.scan` over the table's H
  half-ticks; each device looks up its event (idle / F / B / W) and
  microbatch from the table constants, dispatches through `lax.switch`,
  and exchanges one activation + one cotangent buffer per half-tick on the
  stage ring. The backward is recompute-based per event: B takes the vjp
  w.r.t. the stashed INPUT (producing the upstream cotangent), W the vjp
  w.r.t. the parameters (accumulated into a flat per-chunk gradient) — the
  ZB-H1 event split, which is what lets the zero-bubble table fill the
  drain with W events. Synchronous semantics throughout: every microbatch
  runs at the step-start weights, gradients accumulate, ONE optimizer
  update per step — so no weight stash ring (pipedream's async 1F1B keeps
  its own engine for exactly that feature, but shares this module's stage
  forward builders).

Parity contract (tests/test_pipeline_rt.py, `pipesched` marker):
fill-drain through the runtime is bitwise the legacy gpipe program; the
event schedules are trajectory-pinned against it — the per-step gradient
SUMS match, with drift bounded by f32 reduction-order only (the event
engine accumulates per-microbatch grads in schedule order and divides by M
once; autodiff accumulates in reversed-scan order with the 1/M folded into
the cotangent seed). Documented deviation: B/W recompute uses the
step-current model state, so BN running stats see post-F updates — BN
archs are execution-tested, stateless archs parity-pinned (the same
tradeoff parallel/pipedream.py documents).

The 3-D `data x stage x model` mesh composes because the runtime owns ONLY
the stage axis: dp replicas ride the 'data' axis exactly as in gpipe (the
gradient pmean over 'data' after the scan), and tpp keeps its 'model' axis
inside the switch branches.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from ddlbench_tpu.models.layers import apply_slice
from ddlbench_tpu.parallel.common import (
    cast_input, cast_params, correct_and_count, cross_entropy_loss)
from ddlbench_tpu.parallel.gpipe import (GPipeStrategy, PipeTrainState,
                                         _shard_map, _vary)
from ddlbench_tpu.parallel.packing import pad_vec
from ddlbench_tpu.partition.schedule import (EVENT_BWD_IN, EVENT_BWD_W,
                                             Timetable, make_timetable)


# -- shared stage forward builders (moved from parallel/pipedream.py) ------


def make_stage_fwd(strategy, c: int):
    """Pure chunk forward for vjp-based backward events:
    ``(param_row, state_row, x) -> (y, new_state_row, aux)``.

    Unlike the autodiff-mode branch this has the chunk's TRUE shapes (no
    shared-buffer unpacking, no loss), so `jax.vjp` at a stashed input
    reproduces exactly the per-(microbatch, chunk) backward. ``aux`` is
    the sum of the chunk's MoE router load-balance terms (zero for dense
    chunks). Shared by the event-mode runtime and pipedream's async 1F1B.
    """
    from ddlbench_tpu.models.moe import collect_aux_losses

    layers = strategy.model.layers[strategy.bounds[c]:strategy.bounds[c + 1]]
    p_unravel, p_len = strategy._p_unravels[c], strategy._p_lens[c]
    s_unravel, s_len = strategy._s_unravels[c], strategy._s_lens[c]
    cdtype = strategy.compute_dtype

    def stage_fwd(param_row, state_row, x):
        params = cast_params(p_unravel(param_row[:p_len]), cdtype)
        states = s_unravel(state_row[:s_len])
        aux: list = []
        with collect_aux_losses(aux):
            y, new_states = apply_slice(layers, params, states,
                                        cast_input(x, cdtype), True)
        new_state_row = pad_vec(
            ravel_pytree(new_states)[0].astype(jnp.float32),
            state_row.shape[0]
        )
        return y, new_state_row, sum(aux, jnp.float32(0.0))

    return stage_fwd


def make_stage_fwd_fused(strategy, c: int):
    """Fused-head variant for the LAST chunk (ops/fused_xent.py): applies
    the chunk body, then the head's fused projection+CE — the
    [mb*T, vocab] logits never materialize. Returns None when the model's
    head has no fused path or cfg disables it.

    Signature: ``(param_row, state_row, x, labels)
    -> (obj_sum, ce_sum, correct, new_state_row, aux)``.
    """
    from ddlbench_tpu.models.moe import collect_aux_losses

    head = strategy.model.layers[-1]
    if not (strategy.cfg.fused_head_loss and head.fused_loss is not None):
        return None
    layers = strategy.model.layers[strategy.bounds[c]:strategy.bounds[c + 1]]
    p_unravel, p_len = strategy._p_unravels[c], strategy._p_lens[c]
    s_unravel, s_len = strategy._s_unravels[c], strategy._s_lens[c]
    cdtype = strategy.compute_dtype
    smooth = strategy.cfg.resolved_label_smoothing()

    def stage_fwd_fused(param_row, state_row, x, labels):
        from ddlbench_tpu.parallel.common import fused_slice_loss_sums

        params = cast_params(p_unravel(param_row[:p_len]), cdtype)
        states = s_unravel(state_row[:s_len])
        aux: list = []
        with collect_aux_losses(aux):
            obj_sum, ce_sum, correct, new_states = fused_slice_loss_sums(
                layers, params, states, cast_input(x, cdtype), labels,
                smooth)
        new_state_row = pad_vec(
            ravel_pytree(new_states)[0].astype(jnp.float32),
            state_row.shape[0]
        )
        return (obj_sum, ce_sum, correct, new_state_row,
                sum(aux, jnp.float32(0.0)))

    return stage_fwd_fused


# -- event-mode runtime ----------------------------------------------------


class ScheduledPipelineStrategy(GPipeStrategy):
    """``--pipe-schedule {1f1b, interleaved, zero-bubble, zero-bubble-h2,
    searched}``: the event-mode pipeline runtime (module docstring).
    Inherits gpipe's mesh, stage
    packing, balanced partitioning, eval pipeline (the synchronous
    fill-drain eval is schedule-independent), checkpointing surface and
    state layout — including the hybrid PP x ZeRO-1 row layout and with
    it the elastic-resume reshard surface (train/reshard.py reads
    ``pipe_shard``/``_row_meta``/``dp`` off the strategy, so a
    dp-replica reshape restores event-schedule checkpoints too); only
    the TRAIN step is compiled from the timetable."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self.schedule = self.cfg.pipe_schedule

    # gpipe builds steps lazily on first init(); our hook replaces only the
    # train step builder, so _build_steps needs no override.

    def _timetable(self) -> Timetable:
        # cost-aware timetables (ISSUE 8): per-chunk (f, b, w) half-tick
        # vectors from the profiler / persisted plan ride cfg; None (or
        # all-unit) reproduces the PR 7 unit-cost tables bitwise. The
        # zb-h2 stash and search knobs travel too, so the table the engine
        # compiles is exactly the one the planner priced.
        return make_timetable(self.schedule, self.num_stages,
                              self.num_microbatches, self.vstages,
                              costs=self.cfg.pipe_cost_vectors,
                              stash=self.cfg.zb_h2_stash,
                              search_budget=self.cfg.sched_search_budget,
                              search_seed=self.cfg.sched_search_seed)

    def _make_train_step(self):
        S, M, mb = self.num_stages, self.num_microbatches, self.mb
        V, C = self.vstages, self.num_chunks
        A = self._act_size
        cdtype = self.compute_dtype
        aux_w = self.cfg.moe_aux_weight
        smooth = self.cfg.resolved_label_smoothing()
        mesh = self.mesh
        guard = self._guard
        guarded = guard is not None
        opt_update = self._opt_update

        tt = self._timetable()
        self.timetable = tt  # the loop reads it for --trace tick markers
        ea = tt.engine_arrays()
        # scan over the EXECUTION grid, not the dense half-tick grid: for
        # weighted (cost-aware) tables engine_arrays compresses out the
        # duration-only cells, so the compiled scan length equals the
        # event count, not the predicted makespan
        H = int(ea["ev"].shape[0])
        NQF, NQB = int(ea["nq_f"]), int(ea["nq_b"])
        NSX, NSG = int(ea["ns_x"]), int(ea["ns_g"])
        # When the table glues W to B (1f1b/interleaved: W(c,m) starts the
        # half-tick B(c,m) ENDS — B+1 on unit grids, B + b_cost[c] on
        # cost-weighted ones), ONE vjp at the B event produces both
        # cotangents and the W event just accumulates the stashed
        # param-grad: no second forward recompute. zero-bubble genuinely
        # defers W, so it pays the split-vjp recompute — that tax is the
        # schedule's cost model (PERF.md round 10).
        B_t, W_t = tt.event_times(EVENT_BWD_IN), tt.event_times(EVENT_BWD_W)
        fused_bw = all(
            W_t[k] == B_t[k] + tt.cost_of(EVENT_BWD_IN, k[0]) for k in B_t)
        self._fused_bw = fused_bw  # introspected by the parity tests
        ring_f = [(i, (i + 1) % S) for i in range(S)] if S > 1 else []
        ring_b = [((i + 1) % S, i) for i in range(S)] if S > 1 else []

        stage_fwds = [make_stage_fwd(self, c) for c in range(C)]
        fused_last = make_stage_fwd_fused(self, C - 1)
        in_shapes = [self.shapes[self.bounds[c]] for c in range(C)]
        in_sizes = [mb * math.prod(sh) for sh in in_shapes]
        out_shapes = [self.shapes[self.bounds[c + 1]] for c in range(C)]
        out_sizes = [mb * math.prod(sh) for sh in out_shapes]

        def make_event_fns(c: int):
            """(fwd_fn, bwd_fn, w_fn) for chunk ``c`` — uniform signature
            ``(params, st, g_acc, xst, gst, fwd_q, bwd_q, xs, ys, m, smul)
            -> (st, g_acc, xst, gst, y_out, gx_out, ce_mb, corr_mb)`` over
            the full [V, ...] carries (each fn touches only its static row
            v = c // S)."""
            v = c // S
            first, last = c == 0, c == C - 1
            stage_fwd = stage_fwds[c]
            fused = fused_last if last else None
            in_shape, in_size = in_shapes[c], in_sizes[c]
            out_shape, out_size = out_shapes[c], out_sizes[c]

            def unpack_in(buf):
                return buf[:in_size].reshape(mb, *in_shape)

            def unpack_out(buf):
                return buf[:out_size].reshape(mb, *out_shape)

            def stashed_x(xst, xs, m):
                if first:
                    return lax.dynamic_index_in_dim(xs, m, keepdims=False)
                return unpack_in(lax.dynamic_index_in_dim(
                    xst[v], m % NSX, keepdims=False))

            def obj_scale(smul):
                # guard: loss scale x nan-grad poison carrier rides the
                # cotangent seeds; unguarded branches never touch smul, so
                # the disarmed trace is the exact pre-guard program
                return smul if guarded else jnp.float32(1.0)

            def last_obj(pv, xv, st_row, labels, smul):
                """Per-microbatch training objective on the last chunk
                (smoothed CE + weighted router aux), pre-scaled — the ONE
                definition B and W differentiate. ``st_row`` is the
                step-current state row (recompute deviation, module
                docstring)."""
                if fused is not None:
                    obj_sum, _, _, _, aux = fused(pv, st_row, xv, labels)
                    denom = jnp.maximum(
                        1.0, jnp.sum((labels >= 0).astype(jnp.float32)))
                    obj = obj_sum / denom + aux_w * aux
                else:
                    y, _, aux = stage_fwd(pv, st_row, xv)
                    obj = (cross_entropy_loss(y, labels, smooth)
                           + aux_w * aux)
                return obj * obj_scale(smul)

            def fwd_fn(params, st, g_acc, xst, gst, fwd_q, bwd_q, xs, ys,
                       m, smul):
                if first:
                    x = lax.dynamic_index_in_dim(xs, m, keepdims=False)
                else:
                    x = unpack_in(lax.dynamic_index_in_dim(
                        fwd_q[v], m % NQF, keepdims=False))
                y_out = jnp.zeros((A,), cdtype)
                ce_mb = jnp.zeros((), jnp.float32)
                corr_mb = jnp.zeros((), jnp.int32)
                if last and fused is not None:
                    labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                    _obj, ce_sum, corr_mb, new_st, _aux = fused(
                        params[v], st[v], x, labels)
                    denom = jnp.maximum(
                        1.0, jnp.sum((labels >= 0).astype(jnp.float32)))
                    ce_mb = ce_sum / denom
                else:
                    y, new_st, _aux = stage_fwd(params[v], st[v], x)
                    if last:
                        labels = lax.dynamic_index_in_dim(ys, m,
                                                          keepdims=False)
                        ce_mb = cross_entropy_loss(y, labels)
                        corr_mb = correct_and_count(y, labels)[0]
                    else:
                        y_out = pad_vec(y.astype(cdtype), A)
                st = st.at[v].set(new_st)
                if not first:
                    xst = xst.at[v].set(lax.dynamic_update_index_in_dim(
                        xst[v], pad_vec(x.astype(cdtype), A), m % NSX, 0))
                return (st, g_acc, xst, gst, y_out,
                        jnp.zeros((A,), cdtype), ce_mb, corr_mb)

            def bwd_fn(params, st, g_acc, xst, gst, fwd_q, bwd_q, xs, ys,
                       m, smul):
                """B event. Split mode (zero-bubble): input-grad only —
                the vjp w.r.t. the stashed input ships the upstream
                cotangent, and the incoming cotangent is stashed for the
                deferred W. Fused mode (W glued to B): ONE vjp produces
                both cotangents — gx ships now, gp is stashed for the W
                half-tick to accumulate (no second recompute)."""
                x_st = stashed_x(xst, xs, m)
                gx, gp = None, None
                if last:
                    labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                    obj = lambda pv, xv: last_obj(pv, xv, st[v], labels,
                                                  smul)
                    if fused_bw and first:
                        gp = jax.grad(obj)(params[v])
                    elif fused_bw:
                        gp, gx = jax.grad(obj, argnums=(0, 1))(
                            params[v], x_st)
                    elif not first:
                        gx = jax.grad(
                            lambda xv: obj(params[v], xv))(x_st)
                else:
                    g_cot = unpack_out(lax.dynamic_index_in_dim(
                        bwd_q[v], m % NQB, keepdims=False))
                    seed = None

                    def fwd_of(pv, xv):
                        y, _, aux = stage_fwd(pv, st[v], xv)
                        return y, aux

                    if fused_bw and first:
                        (y, _aux), vjp_fn = jax.vjp(
                            lambda pv: fwd_of(pv, x_st), params[v])
                    elif fused_bw:
                        (y, _aux), vjp_fn = jax.vjp(fwd_of, params[v], x_st)
                    elif not first:
                        (y, _aux), vjp_fn = jax.vjp(
                            lambda xv: fwd_of(params[v], xv), x_st)
                    if fused_bw or not first:
                        seed = (g_cot.astype(y.dtype),
                                jnp.float32(aux_w) * obj_scale(smul))
                    if fused_bw and first:
                        (gp,) = vjp_fn(seed)
                    elif fused_bw:
                        gp, gx = vjp_fn(seed)
                    elif not first:
                        (gx,) = vjp_fn(seed)
                if fused_bw:
                    # param-grad rides the gst ring (same B->W live range
                    # the split mode uses for the cotangent)
                    gst = gst.at[v].set(lax.dynamic_update_index_in_dim(
                        gst[v], gp.astype(jnp.float32), m % NSG, 0))
                elif not last:
                    gst = gst.at[v].set(lax.dynamic_update_index_in_dim(
                        gst[v], pad_vec(g_cot, A), m % NSG, 0))
                gx_out = (jnp.zeros((A,), cdtype) if gx is None
                          else pad_vec(gx.astype(cdtype), A))
                return (st, g_acc, xst, gst, jnp.zeros((A,), cdtype),
                        gx_out, jnp.zeros((), jnp.float32),
                        jnp.zeros((), jnp.int32))

            def w_fn(params, st, g_acc, xst, gst, fwd_q, bwd_q, xs, ys,
                     m, smul):
                """W event. Fused mode: accumulate the param-grad the B
                event stashed — free. Split mode (zero-bubble): the
                weight-grad vjp at the stashed input (and stashed
                cotangent), the deferred work that fills the bubble."""
                if fused_bw:
                    gp = lax.dynamic_index_in_dim(gst[v], m % NSG,
                                                  keepdims=False)
                    g_acc = g_acc.at[v].add(gp)
                    return (st, g_acc, xst, gst, jnp.zeros((A,), cdtype),
                            jnp.zeros((A,), cdtype),
                            jnp.zeros((), jnp.float32),
                            jnp.zeros((), jnp.int32))
                x_st = stashed_x(xst, xs, m)
                if last:
                    labels = lax.dynamic_index_in_dim(ys, m, keepdims=False)
                    gp = jax.grad(
                        lambda pv: last_obj(pv, x_st, st[v], labels,
                                            smul))(params[v])
                else:
                    g_cot = unpack_out(lax.dynamic_index_in_dim(
                        gst[v], m % NSG, keepdims=False))

                    def fwd_of_p(pv):
                        y, _, aux = stage_fwd(pv, st[v], x_st)
                        return y, aux

                    (y, _aux), vjp_fn = jax.vjp(fwd_of_p, params[v])
                    (gp,) = vjp_fn((g_cot.astype(y.dtype),
                                    jnp.float32(aux_w) * obj_scale(smul)))
                g_acc = g_acc.at[v].add(gp.astype(jnp.float32))
                return (st, g_acc, xst, gst, jnp.zeros((A,), cdtype),
                        jnp.zeros((A,), cdtype),
                        jnp.zeros((), jnp.float32),
                        jnp.zeros((), jnp.int32))

            return fwd_fn, bwd_fn, w_fn

        def make_branch(fn):
            def branch(op, xs, ys, m, smul):
                params, st, g_acc, xst, gst, fwd_q, bwd_q = op
                out = fn(params, st, g_acc, xst, gst, fwd_q, bwd_q,
                         xs, ys, m, smul)
                return jax.tree.map(_vary, out)

            return branch

        def idle_branch(op, xs, ys, m, smul):
            params, st, g_acc, xst, gst, fwd_q, bwd_q = op
            out = (st, g_acc, xst, gst, jnp.zeros((A,), cdtype),
                   jnp.zeros((A,), cdtype), jnp.zeros((), jnp.float32),
                   jnp.zeros((), jnp.int32))
            return jax.tree.map(_vary, out)

        # branch order: [idle] + C fwd + C bwd + C w; dispatch index below
        event_fns = [make_event_fns(c) for c in range(C)]
        branches = ([idle_branch]
                    + [make_branch(f[0]) for f in event_fns]
                    + [make_branch(f[1]) for f in event_fns]
                    + [make_branch(f[2]) for f in event_fns])

        # the timetable as on-device constants (tiny int arrays)
        t_ev = jnp.asarray(ea["ev"])
        t_v = jnp.asarray(ea["vrow"])
        t_m = jnp.asarray(ea["mb"])
        t_fav = jnp.asarray(ea["fa_valid"])
        t_far = jnp.asarray(ea["fa_row"])
        t_fam = jnp.asarray(ea["fa_m"])
        t_bav = jnp.asarray(ea["ba_valid"])
        t_bar = jnp.asarray(ea["ba_row"])
        t_bam = jnp.asarray(ea["ba_m"])

        pipe_shard = self.pipe_shard
        dp = self.dp
        meta = getattr(self, "_row_meta", None)
        gather_rows = self._make_gather_rows()

        def inner(params_rows, state_rows, xs, ys, *guard_args):
            # local views -> [V, X] chunk rows (pipedream's convention):
            # V=1 state is [1, L] (P('stage', None), already [V, L]);
            # V>1 is [V, 1, L] (P(None, 'stage', None)). Hybrid
            # PP x ZeRO-1: rows arrive as [V, L/dp] device-major shards
            # and the per-bucket just-in-time all-gather rebuilds them.
            if V == 1:
                params = _vary(params_rows)
                st = _vary(state_rows)
            else:
                params = _vary(params_rows[:, 0])
                st = _vary(state_rows[:, 0])
            if gather_rows is not None:
                params = _vary(gather_rows(params))
            xs = _vary(xs)
            ys = _vary(ys)
            smul = guard_args[0] if guarded else jnp.float32(1.0)
            s_idx = lax.axis_index("stage")
            L = params.shape[1]

            def body(carry, t):
                (st, g_acc, xst, gst, fwd_q, bwd_q, x_in, g_in,
                 ce_acc, corr_acc) = carry

                # 1. absorb last tick's ring arrivals into the m%N queues
                #    (routing is table data — V>1 wrap rows baked in)
                def absorb(q, valid, row, mm, buf, N):
                    q_row = lax.dynamic_index_in_dim(q, row, keepdims=False)
                    q_row = lax.dynamic_update_index_in_dim(
                        q_row, buf, mm % N, 0)
                    q_upd = lax.dynamic_update_index_in_dim(q, q_row, row, 0)
                    return jnp.where(valid, q_upd, q)

                fwd_q = absorb(fwd_q, t_fav[t, s_idx], t_far[t, s_idx],
                               t_fam[t, s_idx], x_in, NQF)
                bwd_q = absorb(bwd_q, t_bav[t, s_idx], t_bar[t, s_idx],
                               t_bam[t, s_idx], g_in, NQB)

                # 2. dispatch this device's event per the table
                ev = t_ev[t, s_idx]
                chunk = t_v[t, s_idx] * S + s_idx
                m = t_m[t, s_idx]
                idx = jnp.where(ev == 0, 0, (ev - 1) * C + chunk + 1)
                op = (params, st, g_acc, xst, gst, fwd_q, bwd_q)
                (st, g_acc, xst, gst, y_out, gx_out, ce_mb,
                 corr_mb) = lax.switch(idx, branches, op, xs, ys, m, smul)
                ce_acc = ce_acc + ce_mb
                corr_acc = corr_acc + corr_mb

                # 3. one activation right, one cotangent left, per half-tick
                if ring_f:
                    x_in = lax.ppermute(y_out, "stage", ring_f)
                    g_in = lax.ppermute(gx_out, "stage", ring_b)
                else:
                    x_in, g_in = y_out, gx_out
                out = (st, g_acc, xst, gst, fwd_q, bwd_q, x_in, g_in,
                       ce_acc, corr_acc)
                return jax.tree.map(_vary, out), None

            init = jax.tree.map(_vary, (
                st,
                jnp.zeros((V, L), jnp.float32),
                jnp.zeros((V, NSX, A), cdtype),
                # fused mode stashes the B event's param-grad rows for W;
                # split mode stashes the incoming cotangent
                (jnp.zeros((V, NSG, L), jnp.float32) if fused_bw
                 else jnp.zeros((V, NSG, A), cdtype)),
                jnp.zeros((V, NQF, A), cdtype),
                jnp.zeros((V, NQB, A), cdtype),
                jnp.zeros((A,), cdtype),
                jnp.zeros((A,), cdtype),
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.int32),
            ))
            (st, g_acc, *_rest, ce_acc, corr_acc) = lax.scan(
                body, init, jnp.arange(H))[0]
            # gpipe-parity reductions: mean objective over M microbatches,
            # dp replicas averaged ('data' pmean), counts summed
            ce = lax.pmean(lax.psum(ce_acc, "stage") / M, "data")
            correct = lax.psum(lax.psum(corr_acc, "stage"), "data")
            if pipe_shard:
                # hybrid PP x ZeRO-1: the post-scan pmean becomes one
                # reduce-scatter PER BUCKET (late buckets' wire time
                # overlaps the drain's remaining compute) — each device
                # keeps its 1/dp device-major slice of the summed
                # gradient, feeding the sharded update outside. The
                # /dp /M matches the replicated engine's pmean-then-/M
                # division order so the trajectories pin.
                parts = []
                for b in range(meta.num_buckets):
                    o, ln = meta.bucket_offsets[b], meta.bucket_padded[b]
                    parts.append(lax.psum_scatter(
                        g_acc[:, o:o + ln], "data", scatter_dimension=1,
                        tiled=True))
                gsh = (jnp.concatenate(parts, axis=1) if len(parts) > 1
                       else parts[0])
                grads = gsh / dp / M
            else:
                grads = lax.pmean(g_acc, "data") / M
            st = lax.pmean(st, "data")  # sync-BN parity with gpipe
            if V == 1:
                return grads, st, ce, correct
            return grads[:, None], st[:, None], ce, correct

        spec = self._chunk_sharding_spec()
        pspec = self._param_spec()
        in_specs = (pspec, spec, P(None, "data"), P(None, "data"))
        if guarded:
            in_specs = in_specs + (P(),)
        pipe = _shard_map(
            inner,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(pspec, spec, P(), P()),
        )

        def train_step(ts: PipeTrainState, xs, ys, lr):
            gstate, smul, opt_in = None, None, ts.opt
            if guard is not None:
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)
            args = (smul,) if guarded else ()
            grads, new_state, ce, correct = pipe(
                ts.params, ts.model_state, xs, ys, *args)
            gm = None
            if guard is not None:
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
            params, opt = opt_update(ts.params, grads, opt_in, lr)
            if guard is not None:
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            valid = jnp.sum((ys >= 0).astype(jnp.float32))
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid),
            }
            if gm is not None:
                metrics.update(gm)
            return PipeTrainState(params, new_state, opt), metrics

        return jax.jit(
            train_step,
            donate_argnums=(0,),
            in_shardings=(self._ts_sharding(), self._batch_sharding,
                          self._batch_sharding, None),
        )
