"""Single-device strategy — the reference's PyTorch baseline.

Parity target: benchmark/mnist/mnist_pytorch.py (train loop :52-99, eval
:102-133): SGD+momentum cross-entropy training with per-interval throughput and
peak-memory logging. Here the entire step (fwd, bwd, update, metrics) is one
jitted function; donated arguments keep params in place in HBM.
"""

from __future__ import annotations

from typing import NamedTuple, Any

import jax
import jax.numpy as jnp

from ddlbench_tpu.config import RunConfig
from ddlbench_tpu.models.layers import LayerModel, init_model
from ddlbench_tpu.parallel.common import make_optimizer


class TrainState(NamedTuple):
    params: Any
    model_state: Any  # BN running stats
    opt: Any  # optimizer-state dict pytree (common.make_optimizer)


class SingleStrategy:
    """strategy='single': one chip, no collectives."""

    def __init__(self, model: LayerModel, cfg: RunConfig):
        from ddlbench_tpu.guard import device_guard

        self.model = model
        self.cfg = cfg
        self.compute_dtype = jnp.dtype(cfg.compute_dtype)
        self._opt_init, opt_update = make_optimizer(cfg)
        smooth = cfg.resolved_label_smoothing()
        guard = self._guard = device_guard(cfg)  # None = pre-guard program

        def train_step(ts: TrainState, x, y, lr):
            from ddlbench_tpu.parallel.common import loss_and_grads

            if guard is None:
                ce, (correct, valid), new_state, grads = loss_and_grads(
                    model, cfg, ts.params, ts.model_state, x, y,
                    self.compute_dtype, smooth)
                params, opt = opt_update(ts.params, grads, ts.opt, lr)
            else:
                # Stability guard: scaled objective (loss scale x nan-grad
                # poison carrier), fused (finite, grad_norm) health pair on
                # the metrics path, anomalous updates dropped in-step under
                # skip / dynamic scaling.
                opt_in, gstate = guard.split_opt(ts.opt)
                smul = guard.smul(gstate, lr)
                ce, (correct, valid), new_state, grads = loss_and_grads(
                    model, cfg, ts.params, ts.model_state, x, y,
                    self.compute_dtype, smooth, obj_scale=smul)
                grads = guard.unscale(grads, smul)
                finite, gnorm = guard.health(ce, grads)
                params, opt = opt_update(ts.params, grads, opt_in, lr)
                params, new_state, opt, gm = guard.commit(
                    finite, gnorm, gstate, (params, new_state, opt),
                    (ts.params, ts.model_state, opt_in))
            # headline loss stays the CE term, comparable across strategies
            metrics = {
                "loss": ce,
                "accuracy": correct.astype(jnp.float32)
                / jnp.maximum(1.0, valid.astype(jnp.float32)),
            }
            if guard is not None:
                metrics.update(gm)
            return TrainState(params, new_state, opt), metrics

        def eval_step(ts: TrainState, x, y):
            from ddlbench_tpu.parallel.common import eval_metrics

            return eval_metrics(model, cfg, ts.params, ts.model_state, x, y,
                                self.compute_dtype)

        self.train_step = jax.jit(train_step, donate_argnums=(0,))
        self.eval_step = jax.jit(eval_step)

    def init(self, key) -> TrainState:
        params, state, _ = init_model(self.model, key)
        opt = self._opt_init(params)
        if self._guard is not None:
            opt = self._guard.attach_opt_state(opt)  # dynamic loss scale
        return TrainState(params, state, opt)

    def shard_batch(self, x, y):
        return x, y

    @property
    def world_size(self) -> int:
        return 1
