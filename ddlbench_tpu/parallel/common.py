"""Shared train-step machinery: loss, SGD with momentum/weight-decay, LR schedule.

Optimizer semantics follow the reference drivers: plain SGD+momentum
(benchmark/mnist/mnist_pytorch.py:153-156), imagenet adds weight decay 1e-4 and
step decay /10 every 30 epochs (benchmark/imagenet/imagenet_pytorch.py:44-50,
225-229). Implemented directly (not via optax) so the same update rule applies
unchanged to packed flat-vector stage parameters in the pipeline strategies.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       smoothing: float = 0.0) -> jax.Array:
    """Mean CE over valid label positions; works for classification
    (logits [B, C], labels [B]) and LM heads (logits [B, T, V], labels [B, T]).

    Positions with ``labels < 0`` are ignored (the seq2seq workload masks
    source-segment positions this way). ``smoothing`` is GNMT-style label
    smoothing (reference seq2seq/train/smoothing.py semantics: smoothed
    target = (1-s) on the gold label, s spread uniformly): loss_tok =
    (1-s)*NLL(gold) - s*mean_v(logp_v). For all-valid labels and s=0 this is
    the plain mean CE.
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    if smoothing:
        nll = (1.0 - smoothing) * nll - smoothing * jnp.mean(logp, axis=-1)
    return jnp.sum(nll * mask) / jnp.maximum(1.0, jnp.sum(mask))


def correct_and_count(logits: jax.Array, labels: jax.Array):
    """(correct int32, valid-position count int32) for eval accumulation."""
    ok = (jnp.argmax(logits, axis=-1) == labels) & (labels >= 0)
    return (jnp.sum(ok.astype(jnp.int32)),
            jnp.sum((labels >= 0).astype(jnp.int32)))


def correct_topk(logits: jax.Array, labels: jax.Array, k: int = 5) -> jax.Array:
    """Count of valid positions whose label is in the top-k logits (prec@k,
    PipeDream eval parity — main_with_runtime.py:639-653).

    Tie handling matches torch.topk's selection order (value descending,
    index ascending): the label ranks after every strictly-greater logit and
    after equal logits at smaller class indices — so degenerate/constant
    logits report ~k/V, not 1.0.
    """
    k = min(k, logits.shape[-1])
    safe = jnp.maximum(labels, 0)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)
    higher = jnp.sum((logits > gold).astype(jnp.int32), axis=-1)
    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    tie_before = jnp.sum(
        ((logits == gold) & (idx < safe[..., None])).astype(jnp.int32), axis=-1
    )
    ok = (higher + tie_before < k) & (labels >= 0)
    return jnp.sum(ok.astype(jnp.int32))


def accum_loss_and_grads(model, params, model_state, x, y, compute_dtype,
                         aux_weight, smoothing, fused, accum_steps: int,
                         remat: bool = False, obj_scale=None):
    """K-way gradient accumulation: split the leading batch axis into K
    micro-steps, scan value_and_grad over them, and average the gradients
    weighted by each micro-step's valid-label count (exact K=1 equivalence;
    uniform weights — Horovod ``DistributedOptimizer(op=hvd.Average,
    backward_passes_per_step=K)`` semantics, imagenet_horovod.py:131-139 —
    whenever all labels are valid, which is every reference workload; the
    matching lr x K scaling lives in train/loop.py). BatchNorm state threads
    sequentially through the micro-steps, exactly as K separate batches
    would. Returns (loss, ce, (correct, valid), new_state, grads).
    """
    K = accum_steps
    B = x.shape[0]
    assert B % K == 0, f"batch {B} not divisible by grad_accum_steps {K}"
    # Micro-step losses are means over that step's VALID label positions, so
    # the K-step average only equals the K=1 full-batch gradient when every
    # micro-step has the same valid count. Weighting each micro-gradient by
    # its valid count restores exact K=1 equivalence for masked token/seq2seq
    # workloads; for image workloads (all labels valid — the only case the
    # reference's backward_passes_per_step ever sees) the weights are uniform
    # and this IS Horovod's equal-weight average.
    # Micro-step k takes every K-th row (reshape [B//K, K, ...], index axis
    # 1): with the batch sharded on axis 0 this keeps each micro-batch's rows
    # local to their device — Horovod's per-worker accumulation — whereas a
    # [K, B//K] leading split would put each micro-step on a fraction of the
    # devices and force a resharding collective per micro-step.
    xs = x.reshape(B // K, K, *x.shape[1:])
    ys = y.reshape(B // K, K, *y.shape[1:])

    from jax import lax

    def step(carry, k):
        st, gsum = carry
        xk = lax.dynamic_index_in_dim(xs, k, axis=1, keepdims=False)
        yk = lax.dynamic_index_in_dim(ys, k, axis=1, keepdims=False)

        def f(p):
            obj, ce, stats, new_st = loss_with_moe_aux(
                model, p, st, xk, yk, True, compute_dtype, aux_weight,
                smoothing, fused, remat)
            if obj_scale is not None:  # stability guard: loss scaling /
                obj = obj * obj_scale  # nan-grad poison carrier
            return obj, (ce, stats, new_st)

        (obj, (ce, (corr, valid), new_st)), g = jax.value_and_grad(
            f, has_aux=True)(params)
        wk = valid.astype(jnp.float32)
        gsum = jax.tree.map(lambda a, b: a + wk * b, gsum, g)
        return (new_st, gsum), (obj, ce, corr, valid)

    init = (model_state, jax.tree.map(jnp.zeros_like, params))
    (new_state, gsum), (objs, ces, corrs, valids) = lax.scan(
        step, init, jnp.arange(K))
    wks = valids.astype(jnp.float32)
    total = jnp.maximum(1.0, jnp.sum(wks))
    grads = jax.tree.map(lambda g: g / total, gsum)
    return (jnp.sum(objs * wks) / total, jnp.sum(ces * wks) / total,
            (jnp.sum(corrs), jnp.sum(valids)), new_state, grads)


def loss_and_grads(model, cfg, params, model_state, x, y, compute_dtype,
                   smoothing, obj_scale=None):
    """One-apply training loss + gradients, dispatching on
    cfg.grad_accum_steps (the shared core of the single/dp/tp/fsdp train
    steps). Returns (ce, (correct, valid), new_state, grads).

    ``obj_scale`` (stability guard) multiplies the training OBJECTIVE only
    — loss scaling plus the nan-grad poison carrier; the returned ``ce``
    metric and the gradients' downstream unscaling are the caller's."""
    if cfg.grad_accum_steps > 1:
        _, ce, stats, new_state, grads = accum_loss_and_grads(
            model, params, model_state, x, y, compute_dtype,
            cfg.moe_aux_weight, smoothing, cfg.fused_head_loss,
            cfg.grad_accum_steps, cfg.remat_layers, obj_scale=obj_scale)
        return ce, stats, new_state, grads

    def loss_fn(p):
        loss, ce, stats, new_state = loss_with_moe_aux(
            model, p, model_state, x, y, True, compute_dtype,
            cfg.moe_aux_weight, smoothing, fused=cfg.fused_head_loss,
            remat=cfg.remat_layers)
        if obj_scale is not None:
            loss = loss * obj_scale
        return loss, (ce, stats, new_state)

    (_, (ce, stats, new_state)), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    return ce, stats, new_state, grads


def make_optimizer(cfg):
    """(init, update) for cfg.resolved_optimizer(), torch semantics.

    * "sgd": torch.optim.SGD — buf = mu*buf + (grad + wd*p); p -= lr*buf
      (the reference's image drivers, mnist_pytorch.py:153-156).
    * "adam": torch.optim.Adam — the reference's translation runtime trains
      with AdamWithWeightStashing (runtime/adam.py,
      translation/main_with_runtime.py:251-256); weight decay is the L2 form
      (added to the gradient), betas/eps from cfg.

    State is a dict pytree ({"m"} or {"m", "v", "step"}) whose m/v leaves
    mirror params — so the same update serves per-layer pytrees AND the
    pipeline strategies' packed row vectors. ``init(params, step_like=None)``
    lets pipelines shape the step counter per stage row (e.g. [S, 1]) so
    every optimizer-state leaf shares the params' stage sharding; the update
    broadcasts it.
    """
    name = cfg.resolved_optimizer()
    mom = cfg.resolved_momentum()
    wd = cfg.resolved_weight_decay()
    b1, b2, eps = cfg.adam_beta1, cfg.adam_beta2, cfg.adam_eps

    zeros = lambda params: jax.tree.map(jnp.zeros_like, params)

    if name == "sgd":

        def init(params, step_like=None):
            return {"m": zeros(params)}

        def update(params, grads, state, lr):
            def upd(p, g, m):
                g = g.astype(p.dtype)
                if wd:
                    g = g + wd * p
                m2 = mom * m + g
                return p - lr * m2, m2

            out = jax.tree.map(upd, params, grads, state["m"])
            new_p = jax.tree.map(lambda o: o[0], out,
                                 is_leaf=lambda x: isinstance(x, tuple))
            new_m = jax.tree.map(lambda o: o[1], out,
                                 is_leaf=lambda x: isinstance(x, tuple))
            return new_p, {"m": new_m}

        return init, update

    def init(params, step_like=None):
        step = (jnp.zeros((), jnp.int32) if step_like is None
                else jnp.zeros(step_like, jnp.int32))
        return {"m": zeros(params), "v": zeros(params), "step": step}

    def update(params, grads, state, lr):
        step = state["step"] + 1
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf
        bc2 = 1.0 - b2 ** stepf

        def upd(p, g, m, v):
            g = g.astype(jnp.float32)
            if wd:
                g = g + wd * p
            m2 = b1 * m + (1.0 - b1) * g
            v2 = b2 * v + (1.0 - b2) * jnp.square(g)
            denom = jnp.sqrt(v2) / jnp.sqrt(bc2) + eps
            return p - (lr / bc1) * m2 / denom, m2, v2

        out = jax.tree.map(upd, params, grads, state["m"], state["v"])
        pick = lambda i: jax.tree.map(lambda o: o[i], out,
                                      is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"m": pick(1), "v": pick(2), "step": step}

    return init, update


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def psum_keepgrad(x, axis):
    """``lax.psum`` whose backward is the identity (pbroadcast semantics).

    Inside shard_map, differentiating a psum'd LOSS must seed each device's
    local backward with the replicated cotangent unchanged: every device
    already holds the same seed (e.g. 1/global_count), and the cross-device
    gradient sum happens once, explicitly, on the gradients themselves
    (psum_scatter in the dp sharded engine). Stock pre-VMA jax transposes
    psum-under-grad to another psum, which would scale such gradients by
    the axis size. Use this for aggregates whose cotangent is replicated
    (loss sums); aggregates with genuinely per-device partial cotangents
    (sync-BN batch statistics) need the mirrored reduction in
    models/layers.sync_batch_mean instead.
    """
    from jax import lax

    return lax.psum(x, axis)


def _psum_keepgrad_fwd(x, axis):
    from jax import lax

    return lax.psum(x, axis), None


def _psum_keepgrad_bwd(axis, _res, ct):
    return (ct,)


psum_keepgrad.defvjp(_psum_keepgrad_fwd, _psum_keepgrad_bwd)


class FlatMeta(NamedTuple):
    """Packing recipe for one pytree <-> one flat f32 vector.

    ``length`` is the unpadded element count; ``padded`` rounds it up so a
    'data'-axis shard is a contiguous equal slice per device. The pad tail
    is mathematically inert through both SGD and Adam: zero params with
    zero grads update to zero (Adam's denominator bottoms out at eps).

    Bucketing (``--comm-buckets K``, the dp comm/compute-overlap engine):
    the flat vector is the concatenation of K contiguous, LEAF-ALIGNED
    buckets, each padded to a multiple of ``world`` so every bucket shards
    into equal contiguous per-device slices and can ride its own collective
    (the per-bucket reduce-scatters/all-gathers are what the latency-hiding
    scheduler interleaves with backward/forward compute).
    ``bucket_leaves[b]`` is the (start, stop) leaf range of bucket b,
    ``bucket_padded[b]`` its padded element count, ``bucket_offsets[b]``
    its start offset in the flat vector; ``padded == sum(bucket_padded)``.
    With one bucket the layout is EXACTLY the pre-bucketing one (single
    tail pad), so ``--comm-buckets 1`` compiles the same program as before.
    Bucketing only moves where pad zeros sit between leaves — never the
    leaf values or any reduction order within a bucket — which is what
    keeps the bucketed f32 path bitwise-pinned to the monolithic one.
    """

    treedef: object
    shapes: tuple
    dtypes: tuple
    sizes: tuple
    length: int
    padded: int
    bucket_leaves: tuple = ((0, 0),)
    bucket_padded: tuple = (0,)
    bucket_offsets: tuple = (0,)

    @property
    def num_buckets(self) -> int:
        return len(self.bucket_padded)


def _bucket_bounds(group_sizes, buckets: int):
    """Greedy contiguous split of ``group_sizes`` (elements per leaf group)
    into <= ``buckets`` groups-aligned chunks balancing element counts.
    Returns group-index boundaries [0, ..., len(group_sizes)]."""
    total = sum(group_sizes)
    buckets = max(1, min(buckets, len(group_sizes) or 1))
    bounds = [0]
    cum = 0  # elements before group i (boundary targets are cumulative)
    acc = 0  # elements in the currently-open bucket (must stay nonzero:
    #          an empty bucket would reduce-scatter a zero-size shard)
    for i, s in enumerate(group_sizes):
        remaining_groups = len(group_sizes) - i
        remaining_buckets = buckets - len(bounds) + 1
        # place boundary k where the CUMULATIVE element count crosses
        # k/buckets of the total (per-boundary fair-share target — a
        # per-bucket threshold drifts: one oversized bucket inflates
        # every later one), but never leave fewer groups than buckets
        # still to fill
        if (len(bounds) <= buckets - 1 and acc > 0
                and (cum >= total * len(bounds) / buckets
                     or remaining_groups <= remaining_buckets)):
            bounds.append(i)
            acc = 0
        cum += s
        acc += s
    bounds.append(len(group_sizes))
    return bounds


def flat_meta(params, world: int, buckets: int = 1,
              leaf_groups=None) -> FlatMeta:
    """Works on concrete leaves and jax.eval_shape ShapeDtypeStructs.

    ``buckets`` splits the packed vector into contiguous leaf-aligned
    buckets (see FlatMeta); ``leaf_groups`` optionally gives the leaf count
    of each alignment group (e.g. leaves per model layer) so bucket
    boundaries fall on LAYER boundaries — the backward then finishes a
    bucket's gradients as one contiguous stretch of layers unwinds. With
    no groups every leaf is its own group. ``buckets=1`` reproduces the
    pre-bucketing layout exactly.
    """
    import math

    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(jnp.dtype(l.dtype) for l in leaves)
    sizes = tuple(math.prod(s) for s in shapes)
    length = int(sum(sizes))

    if leaf_groups is None:
        leaf_groups = [1] * len(leaves)
    assert sum(leaf_groups) == len(leaves), (leaf_groups, len(leaves))
    group_sizes = []
    li = 0
    for g in leaf_groups:
        group_sizes.append(int(sum(sizes[li:li + g])))
        li += g
    # empty-parameter groups (flatten/pool layers) can never host a
    # boundary worth having; merging them right keeps buckets non-trivial
    gbounds = _bucket_bounds(group_sizes, buckets)
    leaf_starts = [0]
    for g in leaf_groups:
        leaf_starts.append(leaf_starts[-1] + g)

    bucket_leaves, bucket_padded, bucket_offsets = [], [], []
    off = 0
    for b in range(len(gbounds) - 1):
        l0 = leaf_starts[gbounds[b]]
        l1 = leaf_starts[gbounds[b + 1]]
        blen = int(sum(sizes[l0:l1]))
        bpad = -(-blen // world) * world if blen else 0
        if bpad == 0 and bucket_leaves:
            # fold an empty bucket into its predecessor
            bucket_leaves[-1] = (bucket_leaves[-1][0], l1)
            continue
        bucket_leaves.append((l0, l1))
        bucket_padded.append(bpad)
        bucket_offsets.append(off)
        off += bpad
    if not bucket_leaves:  # degenerate: a model with zero parameters
        bucket_leaves, bucket_padded, bucket_offsets = [(0, 0)], [0], [0]
    padded = int(sum(bucket_padded))
    return FlatMeta(treedef, shapes, dtypes, sizes, length, padded,
                    tuple(bucket_leaves), tuple(bucket_padded),
                    tuple(bucket_offsets))


def pack_flat(tree, meta: FlatMeta) -> jax.Array:
    """Concatenate the tree's raveled f32 leaves into one [padded] vector
    (bucket-padded layout: each bucket's leaves then its pad zeros).

    The single-bucket path is kept byte-for-byte the pre-bucketing program
    (concat + one tail pad) — ``--comm-buckets 1`` must compile exactly
    the monolithic engine."""
    leaves = jax.tree.leaves(tree)
    if meta.num_buckets == 1:
        flat = jnp.concatenate([l.astype(jnp.float32).ravel()
                                for l in leaves])
        return jnp.pad(flat, (0, meta.padded - meta.length))
    parts = []
    for (l0, l1), bpad in zip(meta.bucket_leaves, meta.bucket_padded):
        parts.extend(l.astype(jnp.float32).ravel() for l in leaves[l0:l1])
        blen = int(sum(meta.sizes[l0:l1]))
        if bpad > blen:
            parts.append(jnp.zeros((bpad - blen,), jnp.float32))
    return jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.float32)


def unpack_flat(flat: jax.Array, meta: FlatMeta):
    """Inverse of pack_flat (drops the pads, restores leaf dtypes).

    Each leaf is sliced from ITS bucket's stretch of the flat vector only —
    under the overlapped dp engine the buckets arrive as separate
    all-gathers, so this dataflow lets the forward's first layers start on
    early buckets while late buckets are still on the wire.
    """
    out = []
    for (l0, l1), boff in zip(meta.bucket_leaves, meta.bucket_offsets):
        off = boff
        for i in range(l0, l1):
            size, shape, dtype = meta.sizes[i], meta.shapes[i], meta.dtypes[i]
            out.append(flat[off:off + size].reshape(shape).astype(dtype))
            off += size
    return jax.tree.unflatten(meta.treedef, out)


def bucket_content_lengths(meta: FlatMeta):
    """Unpadded element count of each bucket — the piece of the LOGICAL
    (concatenated-leaf, pad-free) vector that bucket b carries.

    Leaf-aligned metas (dp ``flat_meta``) sum their leaf sizes; row metas
    (``row_flat_meta``, empty ``sizes``) tile the contiguous [0, length)
    row, so a bucket's content is its overlap with that range. In both
    layouts ``flat = concat_b(logical[c_b:c_b+len_b] + zeros(pad_b))``
    with ``c_b = cumsum(len_b)`` — the invariant train/reshard.py's
    world-size permutation is built on.
    """
    if meta.sizes:
        return [int(sum(meta.sizes[l0:l1])) for l0, l1 in meta.bucket_leaves]
    return [max(0, min(meta.length, off + bp) - off)
            for off, bp in zip(meta.bucket_offsets, meta.bucket_padded)]


def bucket_slice(flat: jax.Array, meta: FlatMeta, b: int) -> jax.Array:
    """Bucket b's [bucket_padded[b]] stretch of a packed flat vector."""
    return flat[meta.bucket_offsets[b]:
                meta.bucket_offsets[b] + meta.bucket_padded[b]]


def unpack_buckets(bucket_arrays, meta: FlatMeta):
    """Pytree from per-bucket flat stretches (each [bucket_padded[b]]).

    The overlapped dp engine's forward: every bucket arrives as its own
    all-gather, and each leaf depends ONLY on its bucket's array — the
    dataflow that lets the first layers start on early buckets while late
    buckets are still on the wire.
    """
    out = []
    for (l0, l1), arr in zip(meta.bucket_leaves, bucket_arrays):
        off = 0
        for i in range(l0, l1):
            size, shape, dtype = meta.sizes[i], meta.shapes[i], meta.dtypes[i]
            out.append(arr[off:off + size].reshape(shape).astype(dtype))
            off += size
    return jax.tree.unflatten(meta.treedef, out)


def to_device_major(flat: jax.Array, meta: FlatMeta, world: int) -> jax.Array:
    """Bucket-layout [padded] vector -> the overlapped engine's DEVICE-MAJOR
    layout: concat over devices of (concat over buckets of that device's
    1/world bucket slice).

    This is the layout per-bucket ``psum_scatter`` outputs naturally produce
    when a device's shard is the concatenation of its bucket slices, and
    the layout the engine keeps params in BETWEEN steps (sharding P('data')
    makes device d own exactly its stretch). With one bucket it is the
    identity permutation.
    """
    parts = []
    for d in range(world):
        for b in range(meta.num_buckets):
            o = meta.bucket_offsets[b]
            bl = meta.bucket_padded[b] // world
            parts.append(flat[o + d * bl:o + (d + 1) * bl])
    return jnp.concatenate(parts) if parts else flat


def from_device_major(flat_dm: jax.Array, meta: FlatMeta,
                      world: int) -> jax.Array:
    """Inverse of :func:`to_device_major` (device-major -> bucket layout)."""
    shard_len = meta.padded // world
    parts = []
    for b in range(meta.num_buckets):
        bo = meta.bucket_offsets[b] // world
        bl = meta.bucket_padded[b] // world
        parts.extend(flat_dm[d * shard_len + bo:d * shard_len + bo + bl]
                     for d in range(world))
    return jnp.concatenate(parts) if parts else flat_dm


def row_flat_meta(length: int, world: int, buckets: int = 1) -> FlatMeta:
    """FlatMeta for an ALREADY-FLAT packed row (the pipeline strategies'
    [S, L] stage-parameter rows), sharded 1/world per device over the pipe
    mesh's 'data' axis in ``buckets`` contiguous pieces.

    The row has no pytree to align to (pack_stages already concatenated
    and padded the stage's leaves to a common L), so buckets are
    near-equal contiguous stretches, each padded-aligned to a multiple of
    ``world`` — the same per-bucket equal-slice property the dp engine's
    leaf-aligned buckets have, which is all to/from_device_major and the
    per-bucket psum_scatter/all_gather need. ``treedef``/``shapes`` are
    empty: unpacking goes through the stage unravels, not unpack_flat."""
    units = -(-max(1, length) // world)  # world-sized units in the row
    buckets = max(1, min(buckets, units))
    base, rem = divmod(units, buckets)
    bucket_padded = []
    bucket_offsets = []
    off = 0
    for b in range(buckets):
        u = base + (1 if b < rem else 0)
        bucket_padded.append(u * world)
        bucket_offsets.append(off)
        off += u * world
    return FlatMeta(None, (), (), (), int(length), int(off),
                    ((0, 0),) * buckets, tuple(bucket_padded),
                    tuple(bucket_offsets))


def device_major_perm(meta: FlatMeta, world: int):
    """Index permutation ``p`` with ``flat[p] == to_device_major(flat)``
    (and its inverse) as numpy arrays — the pipeline strategies apply the
    device-major relayout along the last axis of the packed [.., S, L]
    stage matrix via one jnp.take with a constant index vector."""
    import numpy as np

    idx = []
    for d in range(world):
        for b in range(meta.num_buckets):
            o = meta.bucket_offsets[b]
            bl = meta.bucket_padded[b] // world
            idx.extend(range(o + d * bl, o + (d + 1) * bl))
    perm = np.asarray(idx, np.int32)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.size, dtype=np.int32)
    return perm, inv


def shard_bucket_slice(shard: jax.Array, meta: FlatMeta, world: int,
                       b: int) -> jax.Array:
    """Bucket b's segment of one device's [padded/world] shard.

    The sharded layout is per-bucket: a device's shard is the concatenation
    over buckets of its 1/world slice of each bucket, so bucket b occupies
    ``bucket_offsets[b]/world : (bucket_offsets[b]+bucket_padded[b])/world``
    of the local shard.
    """
    o = meta.bucket_offsets[b] // world
    return shard[o:o + meta.bucket_padded[b] // world]


# ---- int8 wire path (EQuARX-style block-scaled quantized collectives) ----


def sum_safe_qmax(world: int) -> int:
    """Largest per-device quantized magnitude whose WORLD-device sum still
    fits int8: the wire collective (psum / psum_scatter) accumulates IN
    int8, so each device may contribute at most 127 // world — e.g. +-15
    on an 8-way mesh, +-63 on a 2-way one. The lost bits are the price of
    summing on the wire (EQuARX pays the same with block headroom);
    stochastic rounding keeps the estimate unbiased regardless.
    """
    if world > 127:
        raise ValueError(
            f"int8 wire supports up to 127 devices (got {world}): the "
            f"in-dtype collective sum would overflow")
    return max(1, 127 // world)


def stochastic_round_int8(v: jax.Array, key, qmax: int = 127) -> jax.Array:
    """Unbiased stochastic rounding of ``v`` (already scaled into
    [-qmax, qmax]) to int8: floor(v) + Bernoulli(frac(v)).

    E[result] == v elementwise for any v in range, which is what keeps the
    quantized gradient sum an unbiased estimate of the f32 sum; the
    rounding noise is the ONLY stochastic element of the int8 wire and is
    fully determined by ``key`` (derived from the run seed + step counter +
    device/bucket indices in parallel/dp.py), so runs replay bitwise.
    The clip at ``qmax`` only defends against float-division round-off
    pushing an exact-absmax element one ulp past the bound — in-range
    values are never clipped, so no bias is introduced.
    """
    lo = jnp.floor(v)
    frac = v - lo
    u = jax.random.uniform(key, v.shape, dtype=jnp.float32)
    r = lo + (u < frac).astype(jnp.float32)
    return jnp.clip(r, -float(qmax), float(qmax)).astype(jnp.int8)


def quantize_int8(g: jax.Array, key, qmax: int = 127, absmax=None):
    """(q int8, scale f32): absmax-scaled stochastic int8 quantization.

    ``scale = absmax/qmax`` maps the largest-magnitude element to exactly
    +-qmax (representable, zero rounding error); an all-zero block gets
    scale 1 so the division below stays finite. ``absmax`` may be supplied
    by the caller (the dp engine psums a GLOBAL absmax so every device
    shares one scale — a per-device scale could not be summed on the
    wire). Dequantize with ``q.astype(f32) * scale`` — exact for values
    that are integer multiples of the scale (the absmax round-trip
    property pinned by tests/test_comm_overlap.py).
    """
    if absmax is None:
        absmax = jnp.max(jnp.abs(g))
    scale = jnp.where(absmax > 0,
                      absmax.astype(jnp.float32) / qmax, jnp.float32(1.0))
    return stochastic_round_int8(g / scale, key, qmax), scale


def opt_state_sharding(cfg, param_sharding, scalar_sharding):
    """Sharding pytree matching make_optimizer's state: m/v follow the
    params' sharding (which may itself be a pytree), step is scalar-like."""
    sh = {"m": param_sharding}
    if cfg.resolved_optimizer() == "adam":
        sh["v"] = param_sharding
        sh["step"] = scalar_sharding
    return sh


def step_decay_lr(base_lr: float, epoch, step_epochs: int, gamma: float):
    """Step decay /gamma every step_epochs (imagenet_pytorch.py:225-229)."""
    return base_lr * (gamma ** (epoch // step_epochs))


def gradual_warmup_lr(scaled_lr: float, world: int, epoch0: int, step: int,
                      steps_per_epoch: int, warmup_epochs: int) -> float:
    """Goyal-et-al gradual warmup (imagenet_horovod.py:258-275): during the
    first ``warmup_epochs`` the lr ramps linearly, at per-batch granularity,
    from base_lr to the full world-scaled ``scaled_lr`` (= base_lr * world).
    ``epoch0`` is 0-based. Returns scaled_lr untouched past the warmup.
    """
    if epoch0 >= warmup_epochs or world <= 1:
        return scaled_lr
    frac = epoch0 + (step + 1) / max(1, steps_per_epoch)
    lr_adj = (1.0 / world) * (frac * (world - 1) / warmup_epochs + 1.0)
    return scaled_lr * lr_adj


def cast_params(params, dtype):
    """Cast floating-point leaves to the compute dtype (bf16 on TPU)."""
    if dtype is None:
        return params
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        params,
    )


def vary(v, axes):
    """Mark v as varying over any of `axes` it isn't already varying over.

    shard_map's VMA type system requires lax.switch branches and lax.scan
    carries to agree on varying-axes; constants (jnp.zeros) start invariant.
    On pre-VMA jax (no ``jax.typeof``/``lax.pcast``) this is a no-op — see
    ddlbench_tpu/compat.py.
    """
    from ddlbench_tpu.compat import pcast_varying

    return pcast_varying(v, axes)


def cast_input(x, dtype):
    """Cast a batch to the compute dtype; integer inputs (token ids) pass
    through untouched."""
    if dtype is None or not jnp.issubdtype(x.dtype, jnp.floating):
        return x
    return x.astype(dtype)


def head_fusable(model) -> bool:
    """True when the model's last layer offers the fused projection+loss path
    (ops/fused_xent.py) — the LM heads of the token/seq2seq workloads."""
    return model.layers[-1].fused_loss is not None


def fused_slice_loss_sums(layers, params_cast, states, x_cast, labels,
                          smoothing: float, remat: bool = False):
    """Apply layers[:-1], then layers[-1].fused_loss (the fused projection+CE).

    The single home for the fused-head calling convention (also used by the
    pipeline strategies on their loss stage): the head layer must be
    stateless (true for lm_head) and its state entry is passed through
    unchanged. Returns (obj_sum, ce_sum, correct, new_states) — sums over
    valid label positions; callers normalize (and psum first under
    shard_map). Inputs must already be in the compute dtype.
    """
    from ddlbench_tpu.models.layers import apply_slice

    h, new_states = apply_slice(layers[:-1], params_cast[:-1], states[:-1],
                                x_cast, True, remat)
    obj_sum, ce_sum, correct = layers[-1].fused_loss(
        params_cast[-1], h, labels, smoothing)
    return obj_sum, ce_sum, correct, new_states + [states[-1]]


def fused_head_loss_sums(model, params_cast, model_state, x_cast, y,
                         smoothing: float, remat: bool = False):
    """Model-level wrapper of fused_slice_loss_sums; adds the valid count.

    Returns (obj_sum, ce_sum, correct, valid, new_state).
    """
    obj_sum, ce_sum, correct, new_state = fused_slice_loss_sums(
        model.layers, params_cast, model_state, x_cast, y, smoothing, remat)
    valid = jnp.sum((y >= 0).astype(jnp.int32))
    return obj_sum, ce_sum, correct, valid, new_state


def fused_slice_eval_sums(layers, params_cast, states, x_cast, labels):
    """Eval twin of fused_slice_loss_sums: apply layers[:-1] (eval mode),
    then layers[-1].fused_eval. Returns (ce_sum, correct, correct5, valid).
    """
    from ddlbench_tpu.models.layers import apply_slice

    h, _ = apply_slice(layers[:-1], params_cast[:-1], states[:-1], x_cast,
                       False)
    return layers[-1].fused_eval(params_cast[-1], h, labels)


def fused_head_eval_sums(model, params_cast, model_state, x_cast, y):
    """Model-level wrapper of fused_slice_eval_sums."""
    return fused_slice_eval_sums(model.layers, params_cast, model_state,
                                 x_cast, y)


def eval_metrics(model, cfg, params, model_state, x, y, compute_dtype):
    """Shared eval step core for single/dp/tp/fsdp: returns the metric dict
    {loss, correct, correct5, count}. Uses the fused head path (no [N, V]
    logits) when available and enabled."""
    p = cast_params(params, compute_dtype)
    xc = cast_input(x, compute_dtype)
    if cfg.fused_head_loss and model.layers[-1].fused_eval is not None:
        ce_sum, correct, correct5, count = fused_head_eval_sums(
            model, p, model_state, xc, y)
        loss = ce_sum / jnp.maximum(1.0, count.astype(jnp.float32))
        return {"loss": loss, "correct": correct, "correct5": correct5,
                "count": count}
    from ddlbench_tpu.models.layers import apply_model

    logits, _ = apply_model(model, p, model_state, xc, False)
    correct, count = correct_and_count(logits, y)
    return {
        "loss": cross_entropy_loss(logits, y),
        "correct": correct,
        "correct5": correct_topk(logits, y),
        "count": count,
    }


def loss_with_moe_aux(model, params, model_state, x, y, train, compute_dtype,
                      aux_weight, smoothing: float = 0.0, fused: bool = False,
                      remat: bool = False):
    """Apply the model and return (total_loss, ce, (correct, valid), new_state).

    total_loss = cross-entropy (optionally label-smoothed — the training
    objective) + aux_weight * (MoE router load-balance losses collected during
    the apply — zero for dense models). The returned ``ce`` is the *unsmoothed*
    CE so the headline loss metric stays comparable across configurations;
    (correct, valid) are the top-1 metric counts. With ``fused`` (and a model
    whose head supports it — see head_fusable) the projection+loss runs the
    chunked fused path and the full logits are never materialized.
    Shared by every strategy whose loss is computed from one traced apply
    (single/dp/tp/fsdp); sp/ep inline the same pattern because their aux terms
    need a psum over the shard_map axis first.
    """
    from ddlbench_tpu.models.layers import apply_model
    from ddlbench_tpu.models.moe import collect_aux_losses

    p = cast_params(params, compute_dtype)
    xc = cast_input(x, compute_dtype)
    aux: list = []
    if fused and train and head_fusable(model):
        with collect_aux_losses(aux):
            obj_sum, ce_sum, correct, valid, new_state = fused_head_loss_sums(
                model, p, model_state, xc, y, smoothing, remat)
        denom = jnp.maximum(1.0, valid.astype(jnp.float32))
        obj, ce = obj_sum / denom, ce_sum / denom
    else:
        with collect_aux_losses(aux):
            logits, new_state = apply_model(model, p, model_state, xc, train,
                                            remat)
        ce = cross_entropy_loss(logits, y)
        obj = cross_entropy_loss(logits, y, smoothing) if smoothing else ce
        correct, valid = correct_and_count(logits, y)
    return (obj + aux_weight * sum(aux, jnp.float32(0.0)), ce,
            (correct, valid), new_state)
