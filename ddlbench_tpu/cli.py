"""CLI mirroring the reference harness flags.

``run/run/run.sh -b benchmark -f framework -g gpus -n nodes -m model -q queue
-p loginterval -s`` (run.sh:16-47) becomes::

    python -m ddlbench_tpu.cli -b cifar10 -f dp -g 8 -m resnet50 -p 25

plus explicit overrides for batch/microbatch/epochs that the reference passes
through env vars (run_template.sh:70-73). Constraint checks (multi-device only
for dp/gpipe/pipedream — run.sh:51-54) live in RunConfig.validate().
"""

from __future__ import annotations

import argparse
import json
import sys

from ddlbench_tpu.config import (
    ATTENTION_BACKENDS,
    DATASETS,
    HardwareModel,
    RunConfig,
    STRATEGIES,
)
from ddlbench_tpu.models.zoo import MODEL_NAMES


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ddlbench_tpu", description=__doc__)
    p.add_argument("-b", "--benchmark", default="mnist", choices=sorted(DATASETS))
    p.add_argument("-f", "--framework", default="single", choices=STRATEGIES,
                   help="parallelization strategy (reference: pytorch|horovod|gpipe|pipedream)")
    p.add_argument("-g", "--devices", type=int, default=1,
                   help="total number of chips (reference: gpus x nodes)")
    p.add_argument("-m", "--model", default="resnet18", choices=MODEL_NAMES)
    p.add_argument("-p", "--log-interval", type=int, default=25)
    p.add_argument("-s", "--real-data", action="store_true",
                   help="use on-disk data via the native loader (reference -s flag, inverted)")
    p.add_argument("--data-dir", default=None, help="on-disk dataset root (-s mode)")
    p.add_argument("--no-augment", action="store_true",
                   help="disable train-time augmentation in -s mode "
                        "(crop/flip per the reference transforms)")
    p.add_argument("--prefetch-depth", type=int, default=2,
                   help="async input pipeline depth: batches prepared (incl. "
                        "device placement) ahead of the train loop by a "
                        "background thread (data/prefetch.py); 0 = "
                        "synchronous")
    p.add_argument("--no-prefetch", action="store_true",
                   help="shorthand for --prefetch-depth 0 (fully synchronous "
                        "input pipeline)")
    p.add_argument("-e", "--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--micro-batch-size", type=int, default=None)
    p.add_argument("--num-microbatches", type=int, default=None)
    p.add_argument("--stages", type=int, default=None)
    p.add_argument("--virtual-stages", type=int, default=1,
                   help="interleaved schedule (gpipe or pipedream): model "
                        "chunks per device (cuts the pipeline bubble by "
                        "this factor)")
    from ddlbench_tpu.partition.schedule import PIPE_SCHEDULES

    p.add_argument("--pipe-schedule", default="fill-drain",
                   choices=PIPE_SCHEDULES,
                   help="pipeline timetable for -f gpipe, executed by the "
                        "schedule-programmable runtime "
                        "(parallel/pipeline_rt.py): fill-drain = GPipe "
                        "flush (default), 1f1b = synchronous "
                        "one-forward-one-backward, interleaved = 1F1B over "
                        "stages x --virtual-stages chunks, zero-bubble = "
                        "ZB-H1 split backward (weight-grad events fill the "
                        "drain bubble; composes with --virtual-stages), "
                        "zero-bubble-h2 = ZB-H2 (--zb-h2-stash extra "
                        "in-flight microbatches + trailing W deferred past "
                        "the step boundary; steady bubble -> 0), searched "
                        "= budgeted local search seeded by both heuristics "
                        "(partition/schedule_search.py; never worse than "
                        "1f1b/zero-bubble at their activation memory). "
                        "pipedream remains the ASYNC 1F1B engine (weight "
                        "stashing)")
    p.add_argument("--zb-h2-stash", type=int, default=1,
                   help="zero-bubble-h2's extra in-flight activation stash "
                        "(microbatches per chunk): more hides more warmup "
                        "idle, costs that many extra stashed boundary "
                        "activations in the planner's memory term")
    p.add_argument("--sched-search-budget", type=int, default=256,
                   help="searched-schedule move-evaluation budget; same "
                        "budget + --sched-search-seed reproduce the table "
                        "bitwise")
    p.add_argument("--sched-search-seed", type=int, default=0,
                   help="rng seed for the searched schedule's shift moves")
    p.add_argument("--pipe-costs", default="unit", choices=("unit", "profile"),
                   help="timetable cost model for the event schedules: "
                        "unit = F=B=W half-ticks (the classic grids); "
                        "profile = per-chunk F/B/W cost vectors summed "
                        "from the --auto-partition profile over the chosen "
                        "bounds, so uneven stage splits execute on "
                        "cost-weighted timetables (partition/schedule.py)")
    p.add_argument("--schedule-trace", default=None, metavar="PATH",
                   help="a prior run's --trace JSON: --auto-partition's "
                        "schedule advisor folds the MEASURED bubble "
                        "fraction reduced from its pipe_tick spans into "
                        "the ranking (telemetry/bubble.py), outranking "
                        "the analytic value for that schedule")
    p.add_argument("--dp-replicas", type=int, default=1)
    p.add_argument("--tp-size", type=int, default=1,
                   help="composed tensor x pipeline parallelism (gpipe + "
                        "transformer archs): Megatron-slice each stage this "
                        "many ways; -g = dp_replicas x tp_size x stages "
                        "(parallel/tpp.py; add --dp-replicas for 3-D)")
    p.add_argument("--stage-replication", default=None,
                   help="uneven hybrid PPxDP: comma list of per-stage "
                        "replication factors summing to -g, e.g. 1,3 "
                        "(parallel/hetero.py; the reference optimizer's "
                        "heterogeneous plans)")
    p.add_argument("--update-interval", type=int, default=1,
                   help="pipedream macrobatch: accumulate grads over K "
                        "microbatches per optimizer step (reference "
                        "runtime/optimizer.py update_interval)")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--grad-accum-steps", type=int, default=1,
                   help="gradient-accumulation micro-steps per update "
                        "(Horovod backward_passes_per_step parity)")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--optimizer", default=None, choices=("sgd", "adam"),
                   help="default: adam for seq2seq benchmarks (reference "
                        "translation parity), sgd otherwise")
    p.add_argument("--shard-opt-state", action="store_true",
                   help="ZeRO-1 on dp: shard optimizer state over the data "
                        "axis (params stay replicated)")
    p.add_argument("--dp-shard-update", action="store_true",
                   help="explicit sharded weight update (ZeRO-1): on -f dp, "
                        "reduce-scatter grads and update a 1/world slice of "
                        "packed params + optimizer state per chip; on "
                        "-f gpipe, the hybrid PP x ZeRO-1 engine — each "
                        "stage's packed rows + optimizer state shard across "
                        "the pipe mesh's 'data' axis (memory/dp, grad wire "
                        "halved, per-bucket JIT all-gather in the forward)")
    p.add_argument("--allreduce-dtype", default="f32",
                   choices=("f32", "float32", "bf16", "bfloat16", "int8"),
                   help="wire dtype for dp's gradient collectives "
                        "(bf16 = EQuARX-style compressed allreduce, half "
                        "the gradient wire bytes; int8 = per-bucket absmax "
                        "scaling + stochastic rounding, quarter the bytes, "
                        "deterministic under --seed)")
    p.add_argument("--comm-buckets", type=int, default=1, metavar="K",
                   help="dp comm/compute overlap: split the packed flat "
                        "gradient into K layer-aligned buckets, each riding "
                        "its own reduce-scatter as the backward unwinds; "
                        "with --dp-shard-update the params stay sharded "
                        "between steps and the forward all-gathers each "
                        "bucket just-in-time (parallel/dp.py overlapped "
                        "engine). 1 = the monolithic collective program")
    p.add_argument("--warmup-epochs", type=int, default=0,
                   help="gradual lr warmup epochs (Horovod ImageNet parity: "
                        "base lr -> base*world over this many epochs)")
    p.add_argument("--moe-aux-weight", type=float, default=0.01,
                   help="MoE router load-balance loss weight (MoE archs)")
    p.add_argument("--moe-capacity-factor", type=float, default=1.25,
                   help="MoE expert capacity = ceil(cf * tokens / experts)")
    p.add_argument("--label-smoothing", type=float, default=None,
                   help="training-objective label smoothing (default: 0.1 for "
                        "seq2seq benchmarks — GNMT parity — else 0)")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--attention-backend", default="auto",
                   choices=ATTENTION_BACKENDS,
                   help="auto = Pallas flash-attention kernel on TPU")
    p.add_argument("--no-fused-head-loss", action="store_true",
                   help="disable the fused LM-head projection+cross-entropy "
                        "(materialize full logits instead)")
    p.add_argument("--remat-layers", action="store_true",
                   help="jax.checkpoint every layer in the one-apply "
                        "strategies (recompute activations in the backward; "
                        "fits XLA-attention long-context on one chip)")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--jsonl", default=None, help="also write structured metrics JSONL here")
    p.add_argument("--auto-partition", action="store_true",
                   help="profile + hierarchical partitioner choose stage bounds")
    p.add_argument("--plan", default="manual", choices=("manual", "auto"),
                   help="auto = solve the FULL dp/pp/tp mix + stage split "
                        "+ schedule from the profile under the per-chip "
                        "HBM cap (partition/planner.py) and run the "
                        "winner on the existing engines (dp ZeRO-1, "
                        "gpipe/pipeline_rt with --dp-shard-update, tp); "
                        "pass -f gpipe and leave the mix flags unset — "
                        "the decision (all candidates, predicted step "
                        "time, peak bytes/chip, why the winner won) is "
                        "recorded in partition.json")
    p.add_argument("--plan-bounds", default=None, metavar="0,K,...,L",
                   help="explicit per-stage layer bounds for the pipeline "
                        "strategies (stages x virtual-stages + 1 comma "
                        "ints from 0) — execute exactly the split a "
                        "--plan auto run chose")
    p.add_argument("--hbm-gb", type=float, default=None, metavar="G",
                   help="per-chip HBM budget in GiB for the planner / "
                        "auto-partition feasibility gates (default: the "
                        "HardwareModel's 16 GiB v5e constant) — a tight "
                        "cap provably flips --plan auto toward pp>1")
    p.add_argument("--profile-mode", default="flops", choices=("flops", "time"))
    p.add_argument("--trace-dir", default=None,
                   help="write a jax.profiler trace of the run here")
    p.add_argument("--xla-trace-steps", default=None, metavar="A:B",
                   help="capture the jax.profiler trace only for global "
                        "train steps [A, B) instead of the whole run "
                        "(requires --trace-dir; keeps device profiles "
                        "openable on long runs)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write the host-side span trace (train loop, "
                        "prefetch producer, sync/checkpoint phases) as "
                        "Chrome trace-event JSON here — load in Perfetto "
                        "(ui.perfetto.dev) or chrome://tracing")
    p.add_argument("--trace-capacity", type=int, default=200_000,
                   help="span ring-buffer bound; the newest events win "
                        "when a run outlives it")
    p.add_argument("--audit", default=None, metavar="PATH",
                   help="write the compiled train step's audit manifest "
                        "here (telemetry/audit.py: flops, HBM components, "
                        "per-collective ledger from the optimized HLO, "
                        "comm_stats wire-byte tie-out) — AOT introspection "
                        "only, the run itself is untouched")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save a checkpoint per epoch here (orbax, atomic "
                        "commit protocol)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest VALID checkpoint in "
                        "--checkpoint-dir (torn/corrupt ones are skipped); "
                        "an empty dir warns and starts fresh")
    p.add_argument("--checkpoint-every-steps", type=int, default=None,
                   metavar="K",
                   help="also commit a mid-epoch checkpoint every K steps "
                        "(full resume state: bitwise mid-epoch resume)")
    p.add_argument("--keep-checkpoints", type=int, default=None, metavar="N",
                   help="retain only the newest N committed checkpoints "
                        "(older ones + stale .tmp dirs are GC'd)")
    p.add_argument("--elastic-resume", action="store_true",
                   help="topology-portable resume (train/reshard.py): when "
                        "the checkpoint's recorded world shape mismatches "
                        "the current mesh, reshard the ZeRO-1 flat state "
                        "between world sizes (pure permutation, f32 "
                        "bitwise) instead of raising CheckpointShapeError; "
                        "lr world-scaling stays pinned to the launch world")
    p.add_argument("--elastic-slices", type=int, default=None, metavar="E",
                   help="world-invariant reduction order for -f dp "
                        "--dp-shard-update: gradients computed in E fixed "
                        "slices of the global batch and reduced over a "
                        "canonical balanced tree (+ butterfly allreduce), "
                        "so a run checkpointed at world N resumes at world "
                        "M with BITWISE-identical f32 trajectories (E a "
                        "power of two divisible by every world it runs on)")
    p.add_argument("--inject", action="append", default=[],
                   metavar="KIND@EPOCH:STEP",
                   help="deterministic fault injection (repeatable): kill | "
                        "preempt | shrink | grow | ckpt-corrupt | "
                        "prefetch-die | nan-loss | nan-grad | grad-spike | "
                        "slow-host at the given 1-based epoch / 0-based "
                        "step (ddlbench_tpu/faults/; shrink/grow = the "
                        "graceful-checkpoint half of a chaosbench world "
                        "reshape — the supervisor restarts at the new -g)")
    from ddlbench_tpu.guard.policy import ANOMALY_POLICIES
    from ddlbench_tpu.train.watchdog import NAN_POLICIES

    p.add_argument("--anomaly-policy", default=None,
                   choices=ANOMALY_POLICIES,
                   help="stability guard (ddlbench_tpu/guard/): arms "
                        "on-device (finite, grad-norm) detection in the "
                        "train step plus a host EWMA spike detector; skip "
                        "drops anomalous updates in-step (params/opt state "
                        "bitwise untouched), rewind restores the last "
                        "committed checkpoint and replays")
    p.add_argument("--anomaly-budget", type=int, default=3, metavar="K",
                   help="consecutive anomalies (or rewinds for the same "
                        "step) tolerated before the run fails")
    p.add_argument("--loss-scale", default=None, metavar="dynamic|FLOAT",
                   help="loss scaling for bf16 paths: 'dynamic' "
                        "(on-device growth/backoff, overflowed updates "
                        "dropped) or a fixed scale; power-of-two dynamic "
                        "scales keep f32 runs bitwise")
    p.add_argument("--grad-spike-factor", type=float, default=10.0,
                   help="grad-norm spike threshold: factor x EWMA")
    p.add_argument("--nan-policy", default=None, choices=NAN_POLICIES,
                   help="DEPRECATED alias for --anomaly-policy (loss-only "
                        "detection, no on-device guard)")
    p.add_argument("--hang-timeout-s", type=float, default=None,
                   help="abort (with a stack dump) if any step takes longer "
                        "than this; forces a per-step host sync while armed")
    p.add_argument("--log-activations-dir", default=None,
                   help="dump per-layer activations + gradients as npz here "
                        "(torchlogger analog)")
    p.add_argument("--log-activations-freq", type=int, default=1,
                   help="log every N epochs (with --log-activations-dir)")
    p.add_argument("--log-activations-steps", type=int, default=1,
                   help="minibatches to log per logged epoch")
    from ddlbench_tpu.distributed import add_platform_arg

    add_platform_arg(p)
    return p


def _parse_step_window(spec):
    """'A:B' -> (A, B); bounds validated by RunConfig.validate()."""
    if spec is None:
        return None
    try:
        a, b = spec.split(":")
        return int(a), int(b)
    except ValueError:
        raise SystemExit(
            f"--xla-trace-steps expects A:B (two integers); got {spec!r}")


def config_from_args(args) -> RunConfig:
    return RunConfig(
        benchmark=args.benchmark,
        strategy=args.framework,
        arch=args.model,
        num_devices=args.devices,
        synthetic=not args.real_data,
        data_dir=args.data_dir,
        augment=not args.no_augment,
        prefetch_depth=0 if args.no_prefetch else args.prefetch_depth,
        epochs=args.epochs,
        log_interval=args.log_interval,
        batch_size=args.batch_size,
        micro_batch_size=args.micro_batch_size,
        num_microbatches=args.num_microbatches,
        num_stages=args.stages,
        virtual_stages=args.virtual_stages,
        pipe_schedule=args.pipe_schedule,
        zb_h2_stash=args.zb_h2_stash,
        sched_search_budget=args.sched_search_budget,
        sched_search_seed=args.sched_search_seed,
        pipe_costs=args.pipe_costs,
        schedule_trace=args.schedule_trace,
        dp_replicas=args.dp_replicas,
        tp_size=args.tp_size,
        stage_replication=(tuple(int(r) for r in
                                 args.stage_replication.split(","))
                           if args.stage_replication else None),
        update_interval=args.update_interval,
        steps_per_epoch=args.steps_per_epoch,
        grad_accum_steps=args.grad_accum_steps,
        lr=args.lr,
        optimizer=args.optimizer,
        shard_opt_state=args.shard_opt_state,
        dp_shard_update=args.dp_shard_update,
        allreduce_dtype=args.allreduce_dtype,
        comm_buckets=args.comm_buckets,
        warmup_epochs=args.warmup_epochs,
        moe_aux_weight=args.moe_aux_weight,
        moe_capacity_factor=args.moe_capacity_factor,
        label_smoothing=args.label_smoothing,
        compute_dtype=args.dtype,
        attention_backend=args.attention_backend,
        fused_head_loss=not args.no_fused_head_loss,
        remat_layers=args.remat_layers,
        seed=args.seed,
        checkpoint_dir=args.checkpoint_dir,
        resume=args.resume,
        checkpoint_every_steps=args.checkpoint_every_steps,
        keep_checkpoints=args.keep_checkpoints,
        elastic_resume=args.elastic_resume,
        elastic_slices=args.elastic_slices,
        inject=tuple(args.inject),
        nan_policy=args.nan_policy if args.nan_policy is not None else "abort",
        anomaly_policy=args.anomaly_policy,
        anomaly_budget=args.anomaly_budget,
        loss_scale=args.loss_scale,
        grad_spike_factor=args.grad_spike_factor,
        hang_timeout_s=args.hang_timeout_s,
        auto_partition=args.auto_partition,
        plan=args.plan,
        plan_bounds=(tuple(int(b) for b in args.plan_bounds.split(","))
                     if args.plan_bounds else None),
        profile_mode=args.profile_mode,
        hardware=(HardwareModel(hbm_bytes=args.hbm_gb * 1024**3)
                  if args.hbm_gb is not None else HardwareModel()),
        trace=args.trace,
        trace_capacity=args.trace_capacity,
        audit=args.audit,
        trace_dir=args.trace_dir,
        xla_trace_steps=_parse_step_window(args.xla_trace_steps),
        activation_log_dir=args.log_activations_dir,
        activation_log_freq=args.log_activations_freq,
        activation_log_steps=args.log_activations_steps,
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from ddlbench_tpu.distributed import apply_platform, initialize

    if args.nan_policy is not None:
        # deprecated alias for the unified guard surface (warn once per run)
        tail = (" (--anomaly-policy wins; the alias is ignored)"
                if args.anomaly_policy is not None else "")
        print(f"WARNING: --nan-policy is deprecated; use --anomaly-policy "
              f"{args.nan_policy}{tail}", file=sys.stderr, flush=True)

    apply_platform(args.platform)
    if args.comm_buckets > 1:
        # async-collective overlap flags must land in XLA_FLAGS before the
        # first backend touch; no-op on cpu-pinned runs
        from ddlbench_tpu.distributed import apply_comm_flags

        apply_comm_flags(args.platform)

    if args.inject:
        # armed BEFORE initialize() so slow-host can hit the multihost init
        # path; run_benchmark re-arms the same specs (fired state persists)
        from ddlbench_tpu import faults

        faults.arm(args.inject)

    initialize()  # no-op unless DDLB_* multi-host env is set
    cfg = config_from_args(args)
    cfg.validate()

    from ddlbench_tpu.train.loop import run_benchmark
    from ddlbench_tpu.train.metrics import MetricLogger

    # Run manifest (info.txt parity, run.sh:88-96).
    manifest = {k: v for k, v in vars(args).items()}
    print("run manifest: " + json.dumps(manifest), flush=True)

    from ddlbench_tpu.guard import PREEMPT_EXIT_CODE, GracefulPreemption

    logger = MetricLogger(cfg.epochs, cfg.log_interval, jsonl_path=args.jsonl)
    try:
        if args.trace_dir and cfg.xla_trace_steps is None:
            # Whole-run jax.profiler trace — the TPU-native replacement for
            # the reference's hook-based torchprofiler (SURVEY.md §5.1).
            # With --xla-trace-steps the loop opens/closes the capture
            # window itself (train/loop.py _XlaWindow).
            import jax

            with jax.profiler.trace(args.trace_dir):
                result = run_benchmark(cfg, logger=logger)
        else:
            result = run_benchmark(cfg, logger=logger)
    except GracefulPreemption as e:
        # the loop already committed the step-granular checkpoint; the
        # distinct exit code tells supervisors "evicted cleanly, resume me"
        print(f"preempted: {e} (exit {PREEMPT_EXIT_CODE})", flush=True)
        return PREEMPT_EXIT_CODE
    finally:
        # flush + close the --jsonl stream even when a run dies mid-epoch:
        # the structured log is most valuable for exactly those runs
        logger.close()
    result.pop("train_state", None)
    print("result: " + json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
