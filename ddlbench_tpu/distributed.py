"""Multi-host initialization and topology-aware mesh construction.

The reference reaches multi-node through SLURM + ssh + per-rank process spawns
with hand-computed global ranks (run_template.sh:539-558,
pipedream_run.sh:83-101) over NCCL/Gloo/MPI. The TPU equivalent is one process
per host in a single `jax.distributed` world: every process sees the global
device list, and all cross-chip traffic is XLA collectives over ICI (within a
slice) or DCN (across slices/hosts).

`initialize()` is a no-op on single-process runs, so every entry point can
call it unconditionally; on multi-host it reads either explicit env
(DDLB_COORDINATOR, DDLB_NUM_PROCESSES, DDLB_PROCESS_ID) or defers to JAX's
TPU auto-detection.

`make_mesh()` builds meshes with DCN-friendly axis ordering: axes that carry
heavy, latency-tolerant traffic (data parallel) span hosts, while
bandwidth-hungry axes (pipeline stage transfers, sequence rings) stay inside a
slice — the layout the partitioner's cost model assumes.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

_initialized = False


def is_tpu_backend() -> bool:
    """True when the default jax backend is real TPU hardware ('tpu', or
    'axon' — the tunneled-TPU platform). The single home for this check:
    kernel dispatch (flash attention, fused xent) and tools key off it."""
    return jax.default_backend() in ("tpu", "axon")


def add_platform_arg(parser) -> None:
    """Attach the shared --platform flag (one help string for every entry
    point; see apply_platform)."""
    parser.add_argument(
        "--platform", default=None,
        help="force a jax platform (e.g. 'cpu'; combine with "
             "XLA_FLAGS=--xla_force_host_platform_device_count=N for a "
             "virtual mesh)")


def enable_compilation_cache(path: str = "/tmp/ddlbench_xla_cache") -> None:
    """Persistent XLA compilation cache: repeat benchmark invocations reuse
    compiled executables keyed by HLO hash, so a retried run (e.g. after the
    flaky axon tunnel drops mid-bench) skips the multi-minute compile. Safe
    no-op if the running jax lacks the config knobs."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass


# XLA latency-hiding-scheduler knobs for the comm/compute-overlap engine
# (--comm-buckets > 1): convert the bucketed reduce-scatters/all-gathers
# into async collectives that the scheduler interleaves with the
# backward/forward compute instead of running them back-to-back at the
# step boundary. apply_comm_flags gates on the platform: a CPU-only XLA
# build rejects unknown tpu-prefixed flags at backend init.
_COMM_OVERLAP_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_tpu_spmd_threshold_for_windowed_einsum_mib=0",
)


def comm_flags() -> str:
    """The XLA_FLAGS string enabling async-collective overlap on TPU.

    One authoritative home (ISSUE 6): the train CLI / bench drivers apply
    it via :func:`apply_comm_flags` before the first backend touch, and
    the round scripts can export it verbatim
    (``XLA_FLAGS="$(python -c 'from ddlbench_tpu.distributed import
    comm_flags; print(comm_flags())')"``).
    """
    return " ".join(_COMM_OVERLAP_FLAGS)


def apply_comm_flags(platform: Optional[str] = None) -> bool:
    """Append the overlap flags to XLA_FLAGS if a TPU backend is plausible.

    Returns True when applied. Must run BEFORE the first backend touch
    (env-var flags are read at backend init). Requires an AFFIRMATIVE tpu
    signal: a tpu/axon platform pin, or — unpinned — an importable libtpu
    plugin. An unknown tpu-prefixed flag is a fatal parse error at backend
    init on a CPU/GPU-only XLA build, so failing open on "nothing pinned"
    would crash exactly the machines that can't use the flags. Idempotent
    across retried entry points.
    """
    pinned = (platform or os.environ.get("JAX_PLATFORMS", "")).lower()
    if pinned:
        if not any(p in pinned for p in ("tpu", "axon")):
            return False
    else:
        import importlib.util
        if importlib.util.find_spec("libtpu") is None:
            return False
    current = os.environ.get("XLA_FLAGS", "")
    # exact flag-NAME comparison on tokenized flags — a substring test
    # would see the base ..._async_collective_fusion as already present
    # whenever only a longer variant (..._fuse_all_gather) is set
    present = {tok.split("=")[0] for tok in current.split()}
    missing = [f for f in _COMM_OVERLAP_FLAGS
               if f.split("=")[0] not in present]
    if not missing:
        return True
    os.environ["XLA_FLAGS"] = (current + " " + " ".join(missing)).strip()
    return True


def backend_provenance(platform_arg: Optional[str] = None) -> dict:
    """What jax ACTUALLY selected, vs what was asked for. One authoritative
    home for the cpu-fallback classification: recent BENCH rounds silently
    ran on cpu when TPU init hung (ROADMAP "Recent"), poisoning the
    trajectory — every measurement artifact embeds this record and warns
    via :func:`warn_cpu_fallback`. Touches the backend; call only after
    platform pinning (apply_platform / jax.config) is done.
    """
    backend = jax.default_backend()
    cpu_requested = ((platform_arg or "").lower() == "cpu" or
                     os.environ.get("JAX_PLATFORMS", "").lower() == "cpu")
    return {
        "jax_backend": backend,
        "jax_device_count": jax.device_count(),
        "cpu_requested": cpu_requested,
        "cpu_fallback": backend == "cpu" and not cpu_requested,
    }


def warn_cpu_fallback(prov: dict, what: str) -> bool:
    """Loud stderr banner when ``prov`` says cpu ran without being asked
    for. Returns True when the warning fired."""
    import sys

    if not prov.get("cpu_fallback"):
        return False
    print("=" * 72 + f"\nWARNING: {what} is running on the CPU backend "
          "without cpu being asked for\n(--platform/JAX_PLATFORMS) — this "
          "measurement is harness validation only,\nNOT a chip number.\n"
          + "=" * 72, file=sys.stderr, flush=True)
    return True


# Version stamp for every JSON record the tools emit (bench/scalebench/
# servebench/chaosbench/planbench rows and audit manifests). Bump when a
# record's field set changes incompatibly so downstream diff tooling
# (tools/auditbench.py, perf_runs consumers) can refuse mixed ledgers.
RECORD_SCHEMA_VERSION = 1


def record_provenance(platform_arg: Optional[str] = None,
                      what: str = "measurement") -> dict:
    """The one shared record header: ``schema_version`` + the
    :func:`backend_provenance` fields, with the cpu-fallback warning fired
    here so no tool can forget it. Merge into every emitted JSON row."""
    prov = backend_provenance(platform_arg)
    warn_cpu_fallback(prov, what)
    return {"schema_version": RECORD_SCHEMA_VERSION, **prov}


def apply_platform(platform) -> None:
    """Apply a --platform override before the first backend touch. Safe on
    images whose sitecustomize imports jax early: jax.config works until a
    backend is initialized, unlike the JAX_PLATFORMS env var."""
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def force_host_mesh_platform() -> None:
    """Honor an XLA_FLAGS virtual host mesh on images whose sitecustomize
    imports jax at interpreter start.

    There, env vars like JAX_PLATFORMS are read too late, so a requested
    ``--xla_force_host_platform_device_count=N`` CPU mesh would silently lose
    to the default accelerator platform (and entry points would then fail or
    hang waiting on one real chip). Call this before the first backend touch
    from any entry point that should respect the virtual mesh.
    """
    if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass  # backend already initialized; caller sees real devices


def _initialize_with_retry(connect, what: str) -> None:
    """Bounded retry with exponential backoff around one connect attempt.

    A slow-starting peer (host still booting, coordinator not yet bound)
    must not fail the whole multihost run on the first connect error — the
    reference's SLURM launcher simply dies there. Tunables:
    ``DDLB_INIT_ATTEMPTS`` (default 3) total attempts and
    ``DDLB_INIT_BACKOFF_S`` (default 1.0) base delay, doubling per retry.
    The final attempt's exception propagates to the caller.
    """
    import time

    attempts = max(1, int(os.environ.get("DDLB_INIT_ATTEMPTS", "3")))
    base = float(os.environ.get("DDLB_INIT_BACKOFF_S", "1.0"))
    for attempt in range(1, attempts + 1):
        try:
            connect()
            return
        except Exception as e:
            if attempt == attempts:
                raise
            delay = base * 2 ** (attempt - 1)
            print(f"{what} attempt {attempt}/{attempts} failed ({e}); "
                  f"retrying in {delay:.1f}s", flush=True)
            time.sleep(delay)


def initialize() -> bool:
    """Join the jax.distributed world if configured; returns True if multi-host."""
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    # fault hook: `slow-host` injects a delay here, modeling a peer that is
    # slow to reach the coordinator (ddlbench_tpu/faults/)
    from ddlbench_tpu import faults

    faults.multihost_init()
    coord = os.environ.get("DDLB_COORDINATOR")
    nproc = os.environ.get("DDLB_NUM_PROCESSES")
    pid = os.environ.get("DDLB_PROCESS_ID")
    try:
        if coord and nproc and pid:
            _initialize_with_retry(
                lambda: jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=int(nproc),
                    process_id=int(pid),
                ),
                f"jax.distributed.initialize({coord})",
            )
            _initialized = True
        elif os.environ.get("DDLB_AUTO_DISTRIBUTED") == "1":
            # TPU metadata auto-detection
            _initialize_with_retry(lambda: jax.distributed.initialize(),
                                   "jax.distributed.initialize(auto)")
            _initialized = True
    except Exception as e:  # pragma: no cover - depends on environment
        print(f"jax.distributed.initialize failed: {e}", flush=True)
    return jax.process_count() > 1


def make_mesh(axis_sizes: Sequence[Tuple[str, int]],
              devices: Optional[Sequence[jax.Device]] = None,
              dcn_axis: Optional[str] = None) -> Mesh:
    """Build a mesh with the named axes.

    axis_sizes: ordered (name, size) pairs, fastest-varying last. If dcn_axis
    is given and the run spans multiple processes/slices, that axis is mapped
    across hosts via mesh_utils.create_hybrid_device_mesh so its collectives
    ride DCN and everything else stays on ICI.
    """
    names = [n for n, _ in axis_sizes]
    sizes = [s for _, s in axis_sizes]
    total = int(np.prod(sizes))
    devs = list(devices or jax.devices())
    if len(devs) < total:
        raise ValueError(f"need {total} devices, have {len(devs)}")
    devs = devs[:total]

    if dcn_axis is not None and jax.process_count() > 1 and devices is None:
        try:
            from jax.experimental import mesh_utils

            dcn_idx = names.index(dcn_axis)
            per_slice = list(sizes)
            dcn = [1] * len(sizes)
            dcn[dcn_idx] = jax.process_count()
            if per_slice[dcn_idx] % jax.process_count():
                raise ValueError(
                    f"axis {dcn_axis} ({per_slice[dcn_idx]}) must divide across "
                    f"{jax.process_count()} processes"
                )
            per_slice[dcn_idx] //= jax.process_count()
            arr = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn_mesh_shape=dcn
            )
            return Mesh(arr, axis_names=tuple(names))
        except Exception:
            pass  # fall back to plain reshape below

    if devices is None and total > 1:
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(sizes, devices=devs)
            return Mesh(arr, axis_names=tuple(names))
        except Exception:
            pass
    return Mesh(np.array(devs).reshape(sizes), axis_names=tuple(names))


def put_global_batch(x, sharding):
    """Place a host-materialized GLOBAL array onto a (possibly multi-host)
    sharding.

    Single-process: plain device_put. Multi-process: every host materializes
    the same global array (synthetic data is deterministic in (epoch, step) —
    data/synthetic.py), and each host hands jax.make_array_from_callback the
    slices for its addressable shards. Works for any PartitionSpec — batch
    rows for dp/fsdp/ep, sequence columns for sp, replicated for params —
    which is what the reference needs DistributedSampler + broadcast for
    (mnist_horovod.py:207-231).
    """
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def put_global_tree(tree, sharding):
    """Multi-host-safe device_put over a pytree. ``sharding`` is one Sharding
    applied to every leaf, or a prefix pytree of Shardings (jax.device_put's
    prefix convention — each Sharding leaf covers its whole subtree)."""
    from jax.sharding import Sharding

    if jax.process_count() == 1:
        return jax.device_put(tree, sharding)
    if isinstance(sharding, Sharding):
        return jax.tree.map(lambda leaf: put_global_batch(leaf, sharding), tree)
    # prefix pytree: tree.map flattens by the sharding tree's structure and
    # hands each Sharding leaf its corresponding subtree
    return jax.tree.map(
        lambda sh, sub: jax.tree.map(lambda l: put_global_batch(l, sh), sub),
        sharding, tree,
        is_leaf=lambda x: isinstance(x, Sharding),
    )


def local_batch_slice(global_batch: int) -> slice:
    """This process's slice of a host-generated global batch (data staging for
    multi-host: each host materializes only its shard)."""
    n = jax.process_count()
    i = jax.process_index()
    per = global_batch // n
    return slice(i * per, (i + 1) * per)
