"""Serving-trace reducer: TTFT/ITL decomposition + windowed SLO series.

servebench's aggregate TTFT/ITL/goodput say *that* the engine was slow,
never *where* a request's latency went or *when* SLOs were missed. This
module turns a request-lifecycle trace (serve/engine.py under
``ServeConfig.trace``; a Chrome-trace file or the live in-memory tracer)
into the decisions layer:

* **TTFT decomposition** — each request's [submit, first_token) window is
  tiled, exactly, into

  - ``queue``       time in the admission queue (``queue_wait`` spans:
                    arrival wait + post-eviction requeue wait),
  - ``prefill``     steps in which one of the request's prompt chunks ran,
  - ``decode``      pre-first-token decode passes (the full-prefix-hit
                    fast path enters decode directly; eviction replays
                    also land here),
  - ``sched_gap``   everything else: admitted-but-not-scheduled steps
                    (token budget exhausted, lockstep waits on a slower
                    sibling replica).

  Intervals are reduced in the integer domain the engine stamped them in
  (1 model pass = 1000 trace-ns), so components SUM TO TTFT EXACTLY —
  ``decomp_exact`` asserts the tiling (no overlap, no hole mis-count) per
  request and the pinned fixture test fails if instrumentation ever
  drifts.

* **ITL decomposition** — each inter-token gap splits into ``decode``
  (steps whose decode pass the request rode) and ``preempted`` (evicted /
  requeued / re-prefilling time). Per-token times are reconstructed from
  the ``tok``-indexed decode spans; across eviction-recompute replays the
  LAST emission of a token index wins, matching the engine's finished
  records.

* **Windowed SLO attainment + goodput time series** (``--window W``) —
  completions bucketed into [kW, (k+1)W) windows, each with attainment,
  output/good tokens, goodput per unit, and the submissions that arrived
  in the window. Bursty traffic shows attainment DIPPING during the burst
  and recovering after — this series is the input the ROADMAP-2c
  autoscaler consumes, the serving analog of overlap.py/bubble.py's
  one-number reductions.

Works on any Chrome trace-event source with the engine's event taxonomy:
a ``--trace`` file from servebench, a dict, a bare event list, or a live
:class:`~ddlbench_tpu.telemetry.tracer.Tracer`. SLOs default from the
trace metadata servebench embeds (``serve.slo_ttft``/``slo_itl``).
Truncated traces (ring overflow) warn loudly instead of silently
under-counting.

CLI::

    python -m ddlbench_tpu.telemetry.serveview trace.json \
        [--window 32] [--slo-ttft 16] [--slo-itl 2.0] [--per-request]
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from ddlbench_tpu.telemetry.overlap import _merge, _total
from ddlbench_tpu.telemetry.stats import percentile, request_slo_ok

# virtual milli-units: the engine stamps 1 model pass as 1000 trace-ns
# (telemetry/tracer.Tracer.emit), which the exporter renders as 1 µs —
# all interval math here stays in this integer domain so tilings are
# exact, and only the reported values divide back into model-pass units
_SCALE = 1000.0


def _iter_events(trace: Any) -> Iterable[Tuple[str, str, int, int,
                                               Dict[str, Any]]]:
    """(phase, name, t0, t1, args) in integer trace-ns from a trace dict,
    bare event list, or live Tracer (record order preserved — 'last
    emission wins' relies on it)."""
    if hasattr(trace, "events"):  # a live telemetry.Tracer
        for phase, name, t0_ns, dur_ns, _tid, _tname, args in trace.events():
            yield phase, name, int(t0_ns), int(t0_ns + dur_ns), args or {}
        return
    events = trace.get("traceEvents", trace) if isinstance(trace, dict) \
        else trace
    for e in events:
        if not isinstance(e, dict) or "ts" not in e:
            continue
        # export wrote ts = ns / 1e3; round() recovers the exact integer
        t0 = int(round(float(e["ts"]) * 1000.0))
        t1 = t0 + int(round(float(e.get("dur", 0.0)) * 1000.0))
        yield e.get("ph", ""), str(e.get("name", "")), t0, t1, \
            e.get("args") or {}


def _serve_metadata(trace: Any) -> Dict[str, Any]:
    if isinstance(trace, dict):
        meta = trace.get("metadata") or {}
        serve = meta.get("serve")
        if isinstance(serve, dict):
            return serve
    return {}


def collect_requests(trace: Any) -> Dict[Any, Dict[str, Any]]:
    """Per-request event record, keyed by rid. Replicas of a
    ReplicatedServer trace into one file on separate tracks, but the
    dispatcher routes each rid to exactly one replica, so the rid is a
    complete key (workload rids are unique by construction)."""
    reqs: Dict[Any, Dict[str, Any]] = {}
    for phase, name, t0, t1, args in _iter_events(trace):
        rid = args.get("rid")
        if rid is None:
            continue
        r = reqs.setdefault(rid, {
            "rid": rid, "submit": None, "finish": None, "first_token": None,
            "queue": [], "prefill": [], "decode": [], "tok_end": {},
            "evictions": 0, "cached_tokens": 0, "n_tokens": None,
        })
        if name == "submit":
            if r["submit"] is None:
                r["submit"] = t0
        elif name == "queue_wait":
            r["queue"].append((t0, t1))
        elif name == "prefill_chunk":
            r["prefill"].append((t0, t1))
        elif name in ("decode", "verify"):
            # a speculative verify span IS the request's decode time for
            # that pass; it may emit several tokens at once (args.emitted)
            # — all stamped at the pass end, matching the engine's
            # token_times
            r["decode"].append((t0, t1))
            tok = args.get("tok")
            if tok is not None:
                for i in range(int(args.get("emitted", 1))):
                    r["tok_end"][int(tok) + i] = t1  # last emission wins
        elif name == "first_token":
            r["first_token"] = t0  # last wins across recompute replays
            r["tok_end"][0] = t0
        elif name == "evict":
            r["evictions"] += 1
        elif name == "admit":
            r["cached_tokens"] = max(r["cached_tokens"],
                                     int(args.get("cached_tokens", 0)))
        elif name == "finish":
            r["finish"] = t0
            r["n_tokens"] = args.get("n_tokens")
    return reqs


def _clip(iv: List[Tuple[int, int]], w0: int,
          w1: int) -> List[Tuple[int, int]]:
    return [(max(a, w0), min(b, w1)) for a, b in iv
            if min(b, w1) > max(a, w0)]


def decompose_request(r: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """TTFT component tiling for one finished request (None when the
    request never produced a first token — still queued/in flight when
    the trace ended)."""
    if r["submit"] is None or r["first_token"] is None:
        return None
    w0, w1 = r["submit"], r["first_token"]
    ttft = w1 - w0
    queue = _clip(_merge(r["queue"]), w0, w1)
    prefill = _clip(_merge(r["prefill"]), w0, w1)
    decode = _clip(_merge(r["decode"]), w0, w1)
    q, p, d = (int(_total(queue)), int(_total(prefill)),
               int(_total(decode)))
    busy = int(_total(_merge(queue + prefill + decode)))
    gap = ttft - busy
    # exact tiling: the three activity classes are disjoint by
    # construction (queue ends where the admitting step starts; spans
    # stamp integer endpoints), so their sum equals the union and
    # q + p + d + gap == ttft identically. False = instrumentation drift.
    exact = (q + p + d == busy) and gap >= 0
    return {
        "rid": r["rid"],
        "ttft": ttft / _SCALE,
        "queue": q / _SCALE,
        "prefill": p / _SCALE,
        "decode": d / _SCALE,
        "sched_gap": gap / _SCALE,
        "exact": exact,
        "evictions": r["evictions"],
        "cached_tokens": r["cached_tokens"],
    }


def _token_times(r: Dict[str, Any]) -> List[int]:
    """Per-token emission times (trace-ns): the final emission of each
    token index, in index order. Indices are contiguous from 0 for a
    finished request; a hole means the trace window lost events."""
    toks = r["tok_end"]
    return [toks[i] for i in range(len(toks)) if i in toks]


def itl_gaps(r: Dict[str, Any]) -> List[Dict[str, float]]:
    """Inter-token gaps of one request, each split into decode time and
    preempted (evicted/requeued/re-prefilling) time — exact in the
    integer domain, same discipline as the TTFT tiling."""
    times = _token_times(r)
    dec_merged = _merge(r["decode"])
    out = []
    for g0, g1 in zip(times, times[1:]):
        dec = int(_total(_clip(dec_merged, g0, g1)))
        out.append({"gap": (g1 - g0) / _SCALE, "decode": dec / _SCALE,
                    "preempted": (g1 - g0 - dec) / _SCALE})
    return out


def _pctl(samples: List[float]) -> Dict[str, float]:
    return {
        "p50": percentile(samples, 50.0),
        "p95": percentile(samples, 95.0),
        "p99": percentile(samples, 99.0),
        "mean": sum(samples) / len(samples) if samples else 0.0,
    }


def _slo_record(r: Dict[str, Any]) -> Dict[str, Any]:
    """A trace-derived request as the record shape
    ``telemetry/stats.request_slo_ok`` takes — ONE predicate decides
    "met the SLO" for servebench's goodput, the engine's snapshot, and
    the windowed attainment here."""
    return {
        "arrival": r["submit"] / _SCALE,
        "first_token_t": r["first_token"] / _SCALE,
        "token_times": [t / _SCALE for t in _token_times(r)],
    }


def timeline(reqs: Dict[Any, Dict[str, Any]], *, window: float,
             slo_ttft: Optional[float] = None,
             slo_itl: Optional[float] = None) -> List[Dict[str, Any]]:
    """Windowed SLO-attainment + goodput series: tumbling buckets of
    ``window`` virtual units over [0, last finish]. Every bucket is
    emitted (empty ones as zeros) so the series is a continuous signal —
    the autoscaler input named by ROADMAP item 2c."""
    if window <= 0:
        raise ValueError(f"window must be > 0, got {window}")
    done = [r for r in reqs.values()
            if r["finish"] is not None and r["first_token"] is not None
            and r["submit"] is not None]
    submits = sorted(r["submit"] / _SCALE for r in reqs.values()
                     if r["submit"] is not None)
    if not done and not submits:
        return []
    hi = max([r["finish"] / _SCALE for r in done] + submits)
    n_buckets = int(hi // window) + 1
    buckets = [{
        "t0": k * window, "t1": (k + 1) * window, "submitted": 0,
        "completed": 0, "slo_ok": 0, "attainment": 0.0,
        "tokens": 0, "good_tokens": 0, "goodput_tokens_per_unit": 0.0,
    } for k in range(n_buckets)]
    for t in submits:
        buckets[min(int(t // window), n_buckets - 1)]["submitted"] += 1
    for r in done:
        b = buckets[min(int((r["finish"] / _SCALE) // window),
                        n_buckets - 1)]
        n_tok = (r["n_tokens"] if r["n_tokens"] is not None
                 else len(r["tok_end"]))
        b["completed"] += 1
        b["tokens"] += n_tok
        if request_slo_ok(_slo_record(r), slo_ttft, slo_itl):
            b["slo_ok"] += 1
            b["good_tokens"] += n_tok
    for b in buckets:
        b["attainment"] = (b["slo_ok"] / b["completed"]
                           if b["completed"] else 0.0)
        b["goodput_tokens_per_unit"] = b["good_tokens"] / window
    return buckets


def breakdown(trace: Any, *, slo_ttft: Optional[float] = None,
              slo_itl: Optional[float] = None,
              window: Optional[float] = None,
              per_request: bool = True) -> Dict[str, Any]:
    """Reduce a serving trace to its latency decomposition + SLO series.

    ``trace``: Chrome trace dict, bare event list, or a live Tracer.
    SLOs default from the ``serve`` metadata block servebench embeds when
    the trace dict carries one. Returns requests/incomplete counts,
    per-component TTFT percentiles, pooled ITL decode/preempted
    percentiles, the exactness flag (every request's components tiled its
    TTFT), optionally the per-request table and — with ``window`` — the
    windowed timeline.
    """
    meta = _serve_metadata(trace)
    if slo_ttft is None:
        slo_ttft = meta.get("slo_ttft")
    if slo_itl is None:
        slo_itl = meta.get("slo_itl")
    reqs = collect_requests(trace)
    decomps = []
    incomplete = 0
    itl_decode: List[float] = []
    itl_preempted: List[float] = []
    for r in reqs.values():
        d = decompose_request(r)
        if d is None:
            incomplete += 1
            continue
        decomps.append(d)
        for g in itl_gaps(r):
            itl_decode.append(g["decode"])
            itl_preempted.append(g["preempted"])
    from ddlbench_tpu.telemetry.export import trace_truncation

    out: Dict[str, Any] = {
        "requests": len(decomps),
        "incomplete": incomplete,
        "decomp_exact": all(d["exact"] for d in decomps),
        "ttft": {comp: _pctl([d[comp] for d in decomps])
                 for comp in ("ttft", "queue", "prefill", "decode",
                              "sched_gap")},
        "itl": {"decode": _pctl(itl_decode),
                "preempted": _pctl(itl_preempted)},
        "slo_ttft": slo_ttft,
        "slo_itl": slo_itl,
        "dropped_events": trace_truncation(trace),
    }
    if per_request:
        out["per_request"] = sorted(decomps, key=lambda d: d["rid"])
    if window is not None:
        out["window"] = window
        out["timeline"] = timeline(reqs, window=window, slo_ttft=slo_ttft,
                                   slo_itl=slo_itl)
    return out


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="serveview", description=__doc__)
    p.add_argument("trace", help="Chrome trace-event JSON file written by "
                                 "servebench --trace (or any trace with "
                                 "the engine's event taxonomy)")
    p.add_argument("--window", type=float, default=None,
                   help="emit the windowed SLO/goodput timeline with "
                        "buckets this many virtual units wide")
    p.add_argument("--slo-ttft", type=float, default=None,
                   help="TTFT SLO in virtual units (default: the trace's "
                        "embedded serve metadata)")
    p.add_argument("--slo-itl", type=float, default=None,
                   help="mean inter-token-latency SLO in virtual units "
                        "(default: the trace's embedded serve metadata)")
    p.add_argument("--per-request", action="store_true",
                   help="include the per-request component table "
                        "(omitted by default to keep the JSON small)")
    args = p.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    from ddlbench_tpu.telemetry.export import warn_if_truncated

    warn_if_truncated(doc, "serveview")
    out = breakdown(doc, slo_ttft=args.slo_ttft, slo_itl=args.slo_itl,
                    window=args.window, per_request=args.per_request)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
