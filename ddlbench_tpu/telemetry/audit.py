"""Compiled-program audit plane: tie every analytic model to the program
XLA actually built.

The framework prices everything analytically — ``train/comm_stats.py``
wire bytes, the planner's HBM model, ``serve.pool_page_bytes`` KV
accounting — but on-chip validation is queued behind the TPU tunnel.
XLA already knows the truth at compile time: ``compiled.cost_analysis()``
/ ``memory_analysis()`` give exact flops and buffer bytes on ANY backend,
and the optimized HLO text lists every collective with its shape, dtype
and replica groups. This module walks those out into a per-program
**audit manifest** and cross-checks the analytic models against it:

* :func:`collective_ledger` — parse the optimized HLO into
  :class:`CollectiveOp` records (kind, dtype, shape, per-participant
  payload bytes, replica groups incl. the iota ``[G,g]<=[N]`` form,
  ring-model wire bytes).
* :func:`program_manifest` — flops / bytes-accessed / memory components
  / the ledger, with graceful degradation: on backends where
  cost_analysis or memory_analysis are unavailable the fields are
  ``None``, never a ``KeyError``.
* :func:`reconcile_train` — per-engine exact tie-outs of ``comm_stats``
  against the ledger (dp ZeRO-1 bucketed, int8 incl. scale sidecars,
  gpipe conveyor + padded-row sync, tp per-collective payload classes).
  GSPMD-compiled engines (replicated dp, monolithic ZeRO-1) lower to an
  irregular collective soup and are reported ``tieable: False`` by
  design — exact ties target the explicit shard_map engines.
* :func:`serve_pool_audit` — ``pool_page_bytes`` vs the actual pool
  buffer bytes the compiled serve programs take as arguments, across
  tp / kv_dtype layouts (int8 payload exactly f32/4).
* :func:`planner_stage_hbm_audit` — signed per-stage error of the
  planner's HBM model vs ``memory_analysis()``, recorded in the
  partition.json idiom.
* :func:`diff_manifests` — the regression gate ``auditbench diff``
  uses: unexplained growth in flops / peak HBM / wire bytes / collective
  counts between two manifests exits nonzero.

Wire conventions (ring model, matching ``comm_stats``): for one op with
``G`` replica groups of size ``g`` and per-participant payload ``p``
bytes — all-reduce ``G * 2(g-1)/g * p``; reduce-scatter (HLO shows the
per-shard OUTPUT, full = out*g) ``G * (g-1) * out``; all-gather (HLO
shows the gathered output = full) ``G * (g-1)/g * out``; all-to-all
``G * (g-1) * p``; collective-permute ``payload * n_pairs``. Dynamic
trip counts (conveyors inside while loops) are the ANALYTIC side's job:
the ledger records the static op, ``comm_stats``'s physical_* twins
price op x trips.
"""

from __future__ import annotations

import json
import math
import os
import re
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

AUDIT_SCHEMA_VERSION = 1

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1, "u4": 1, "s4": 1,
}

_KINDS = ("all-reduce", "reduce-scatter", "all-gather",
          "collective-permute", "all-to-all")

_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<shape>\([^)]*\)|[\w\[\],{}:]+)\s+"
    r"(?P<kind>" + "|".join(_KINDS) + r")(?P<phase>-start|-done)?\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[\d,{}]*\})\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")


@dataclass
class CollectiveOp:
    """One collective instruction walked out of the optimized HLO."""
    name: str
    kind: str
    dtype: str
    shape: Tuple[int, ...]
    elements: int
    payload_bytes: float          # per-participant bytes as shown in HLO
    scalar: bool                  # metric psums etc. (1 element)
    groups: Optional[List[List[int]]] = None
    n_groups: int = 1
    group_size: int = 1
    n_pairs: int = 0              # collective-permute only
    axes: Optional[str] = None    # mesh axes resolved from replica groups
    wire_bytes: float = 0.0       # ring-model wire for one execution


def _parse_shape(tok: str) -> Tuple[str, Tuple[int, ...], int, float]:
    """Parse an HLO result-shape token (possibly a tuple) into
    (dtype, dims-of-first-component, total elements, total bytes)."""
    comps = _SHAPE_RE.findall(tok)
    if not comps:
        return "unknown", (), 0, 0.0
    total_elems, total_bytes = 0, 0.0
    for dt, dims in comps:
        if dt not in _DTYPE_BYTES:      # token[], tuple wrappers, opaque
            continue
        shape = tuple(int(d) for d in dims.split(",") if d)
        n = math.prod(shape) if shape else 1
        total_elems += n
        total_bytes += n * _DTYPE_BYTES[dt]
    dt0, dims0 = comps[0]
    shape0 = tuple(int(d) for d in dims0.split(",") if d)
    return dt0, shape0, total_elems, total_bytes


def _parse_replica_groups(line: str) -> Optional[List[List[int]]]:
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        inner = m.group(1)
        return [[int(x) for x in grp.split(",") if x]
                for grp in re.findall(r"\{([\d,]*)\}", "{" + inner + "}")]
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        # iota form: arange(prod(dims)).reshape(dims).T(perm).reshape(G, g)
        num_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = list(range(math.prod(dims)))
        if m.group(4):
            import numpy as np
            perm = [int(x) for x in m.group(4).split(",")]
            ids = list(np.arange(math.prod(dims)).reshape(dims)
                       .transpose(perm).reshape(-1))
        return [[int(ids[i * group_size + j]) for j in range(group_size)]
                for i in range(num_groups)]
    return None


def _ring_wire(kind: str, payload: float, g: int, n_groups: int,
               n_pairs: int) -> float:
    if kind == "collective-permute":
        return payload * n_pairs
    if g <= 1:
        return 0.0
    if kind == "all-reduce":
        per = 2.0 * (g - 1) / g * payload
    elif kind == "reduce-scatter":
        per = (g - 1) * payload       # payload = per-shard output
    elif kind == "all-gather":
        per = (g - 1) / g * payload   # payload = gathered output
    elif kind == "all-to-all":
        per = (g - 1) * payload
    else:
        per = 0.0
    return n_groups * per


def resolve_axes(groups: Optional[List[List[int]]],
                 mesh_axes: Sequence[Tuple[str, int]]) -> Optional[str]:
    """Which mesh-axis subset a replica-group partition varies over.

    Compares ``groups`` (as an unordered partition of device ids) against
    the canonical partition of the row-major mesh for every non-empty
    subset of axes; returns '+'-joined axis names on a match, else None.
    """
    if not groups or not mesh_axes:
        return None
    names = [n for n, _ in mesh_axes]
    sizes = [s for _, s in mesh_axes]
    world = math.prod(sizes)
    if sum(len(g) for g in groups) != world:
        return None
    want = frozenset(frozenset(g) for g in groups)
    import itertools
    import numpy as np
    arr = np.arange(world).reshape(sizes)
    k = len(names)
    for r in range(1, k + 1):
        for subset in itertools.combinations(range(k), r):
            rest = [i for i in range(k) if i not in subset]
            part = arr.transpose(rest + list(subset)).reshape(
                -1, math.prod(sizes[i] for i in subset))
            got = frozenset(frozenset(int(x) for x in row) for row in part)
            if got == want:
                return "+".join(names[i] for i in subset)
    return None


def collective_ledger(hlo_text: str,
                      mesh_axes: Optional[Sequence[Tuple[str, int]]] = None,
                      ) -> List[CollectiveOp]:
    """Walk the optimized HLO text into one record per collective op."""
    ops: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if not m or m.group("phase") == "-done":
            continue
        kind = m.group("kind")
        dtype, shape, elems, payload = _parse_shape(m.group("shape"))
        groups = _parse_replica_groups(line)
        n_pairs = 0
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(line)
            if pm:
                n_pairs = pm.group(1).count("{")
        n_groups = len(groups) if groups else 1
        g = len(groups[0]) if groups else 1
        op = CollectiveOp(
            name=m.group("name"), kind=kind, dtype=dtype, shape=shape,
            elements=elems, payload_bytes=payload,
            # rank-0 single elements are the metric/scale psums; a
            # rank>=1 single element (a padded [1] state row) is payload
            scalar=(elems <= 1 and not shape), groups=groups,
            n_groups=n_groups, group_size=g, n_pairs=n_pairs,
            axes=resolve_axes(groups, mesh_axes or ()),
        )
        op.wire_bytes = _ring_wire(kind, payload, g, n_groups, n_pairs)
        ops.append(op)
    return ops


def _mesh_axes_of(mesh) -> Optional[List[Tuple[str, int]]]:
    if mesh is None:
        return None
    try:
        return [(str(k), int(v)) for k, v in dict(mesh.shape).items()]
    except Exception:
        return None


def _cost_dict(compiled) -> Optional[Dict[str, float]]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    return ca


def _memory_dict(compiled) -> Optional[Dict[str, Optional[float]]]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = {
        "argument_bytes": "argument_size_in_bytes",
        "output_bytes": "output_size_in_bytes",
        "temp_bytes": "temp_size_in_bytes",
        "alias_bytes": "alias_size_in_bytes",
        "generated_code_bytes": "generated_code_size_in_bytes",
    }
    out: Dict[str, Optional[float]] = {}
    for k, attr in fields.items():
        v = getattr(ma, attr, None)
        out[k] = float(v) if v is not None else None
    present = [out[k] for k in ("argument_bytes", "output_bytes",
                                "temp_bytes") if out[k] is not None]
    if present:
        out["peak_bytes"] = (sum(present)
                             - (out.get("alias_bytes") or 0.0))
    else:
        out["peak_bytes"] = None
    return out


def program_manifest(compiled, name: str, mesh=None,
                     extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The audit manifest for one compiled program.

    Degrades gracefully: any introspection surface the backend lacks
    yields ``None`` fields (and an empty ledger when the HLO text is
    unavailable) — never a KeyError.
    """
    import jax

    cost = _cost_dict(compiled)
    mem = _memory_dict(compiled)
    mesh_axes = _mesh_axes_of(mesh)
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = None
    ledger = collective_ledger(hlo, mesh_axes) if hlo else []
    totals: Dict[str, Dict[str, float]] = {}
    scalar_counts: Dict[str, int] = {}
    wire_total = 0.0
    for op in ledger:
        if op.scalar and op.kind == "all-reduce":
            scalar_counts[op.dtype] = scalar_counts.get(op.dtype, 0) + 1
            continue
        t = totals.setdefault(op.kind, {"count": 0, "payload_bytes": 0.0,
                                        "wire_bytes": 0.0})
        t["count"] += 1
        t["payload_bytes"] += op.payload_bytes
        t["wire_bytes"] += op.wire_bytes
        wire_total += op.wire_bytes
    return {
        "audit_schema_version": AUDIT_SCHEMA_VERSION,
        "name": name,
        "jax_version": jax.__version__,
        "jaxlib_version": getattr(__import__("jaxlib"), "__version__", None),
        "backend": jax.default_backend(),
        "mesh_axes": mesh_axes,
        "flops": (float(cost["flops"])
                  if cost and "flops" in cost else None),
        "bytes_accessed": (float(cost["bytes accessed"])
                           if cost and "bytes accessed" in cost else None),
        "memory": mem,
        "hlo_available": hlo is not None,
        "collectives": [asdict(op) for op in ledger],
        "collective_totals": totals,
        "scalar_collectives": scalar_counts,
        "wire_bytes_total": wire_total,
        **(extra or {}),
    }


def lower_manifest(jitfn, args: Sequence[Any], name: str, mesh=None,
                   extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """AOT-lower + compile ``jitfn(*args)`` and manifest it. Lowering
    never executes, so donated arguments are safe to reuse after."""
    compiled = jitfn.lower(*args).compile()
    return program_manifest(compiled, name, mesh=mesh, extra=extra)


# ---------------------------------------------------------------------------
# comm_stats tie-outs per engine
# ---------------------------------------------------------------------------


def _check(name: str, expected: float, actual: float,
           tol: float = 0.0) -> Dict[str, Any]:
    ok = (abs(actual - expected) <= tol * max(abs(expected), 1.0)
          if tol else actual == expected)
    return {"check": name, "expected": float(expected),
            "actual": float(actual), "ok": bool(ok)}


def _ops(manifest: Dict[str, Any], kind: Optional[str] = None,
         scalar: Optional[bool] = None) -> List[Dict[str, Any]]:
    out = []
    for op in manifest.get("collectives", []):
        if kind is not None and op["kind"] != kind:
            continue
        if scalar is not None and op["scalar"] != scalar:
            continue
        out.append(op)
    return out


def reconcile_train(strategy, manifest: Dict[str, Any]) -> Dict[str, Any]:
    """Exact per-collective tie-out of ``comm_stats`` vs the ledger.

    Returns ``{"engine", "tieable", "checks": [...], "unexplained": [...],
    "comm_stats": {...}}``; ``ok`` is the AND of all checks AND an empty
    unexplained list. Engines compiled through GSPMD sharding propagation
    (replicated dp, monolithic ZeRO-1 without the explicit wire engine)
    produce compiler-chosen collective soup — those come back
    ``tieable: False`` with the manifest still attached.
    """
    from ddlbench_tpu.train.comm_stats import comm_stats

    name = type(strategy).__name__
    cs = comm_stats(strategy)
    res: Dict[str, Any] = {"engine": name, "tieable": True,
                           "checks": [], "unexplained": [],
                           "comm_stats": cs}
    checks: List[Dict[str, Any]] = res["checks"]
    if not manifest.get("hlo_available"):
        res["tieable"] = False
        res["ok"] = False
        return res

    if name == "DPStrategy":
        meta = getattr(strategy, "_flat_meta", None)
        if meta is None:
            res["tieable"] = False     # GSPMD pmean engine
            res["ok"] = False
            return res
        import numpy as np
        r = strategy.world_size
        nb = int(meta.num_buckets)
        wire_dtype = np.dtype(getattr(strategy, "wire_dtype", "float32"))
        int8 = wire_dtype == np.dtype(np.int8)
        wire_name = {1: "s8", 2: "bf16", 4: "f32"}.get(
            wire_dtype.itemsize, "f32")
        if getattr(strategy, "shard_update", False):
            rs = _ops(manifest, "reduce-scatter")
            ag = [op for op in _ops(manifest, "all-gather")
                  if op["dtype"] == "f32"]
            checks.append(_check("rs_op_count", nb, len(rs)))
            checks.append(_check("ag_op_count", nb, len(ag)))
            checks.append(_check(
                "rs_wire_bytes", cs["physical_reduce_scatter_bytes"],
                sum(op["wire_bytes"] for op in rs)))
            checks.append(_check(
                "ag_wire_bytes", cs["physical_all_gather_bytes"],
                sum(op["wire_bytes"] for op in ag)))
            checks.append(_check(
                "rs_wire_dtype", nb,
                sum(1 for op in rs if op["dtype"] == wire_name)))
        else:
            ar = _ops(manifest, "all-reduce", scalar=False)
            checks.append(_check(
                "ar_wire_bytes", cs["physical_allreduce_bytes"],
                sum(op["wire_bytes"] for op in ar
                    if op["dtype"] == wire_name)))
        if int8:
            # scale sidecars: exactly one scalar f32 psum per bucket on
            # top of the 2 scalar f32 metric psums (loss/norm)
            n_f32 = manifest.get("scalar_collectives", {}).get("f32", 0)
            checks.append(_check("scalar_f32_psums", 2 + nb, n_f32))
            checks.append(_check(
                "scale_wire_bytes", cs["scale_bytes"],
                (n_f32 - 2) * (2.0 * (r - 1) / r * 4.0)))

    elif name == "GPipeStrategy":
        itemsize = strategy.compute_dtype.itemsize
        S, dp = strategy.num_stages, strategy.dp
        M = strategy.num_microbatches
        V = strategy.num_chunks // S
        T = M * V + S - 1
        cp = _ops(manifest, "collective-permute")
        act = float(strategy._act_size) * itemsize
        checks.append(_check("cp_op_count", 2, len(cp)))
        for op in cp:
            checks.append(_check(
                f"cp_payload[{op['name']}]", act, op["payload_bytes"]))
            checks.append(_check(
                f"cp_pairs[{op['name']}]", (S - 1) * dp, op["n_pairs"]))
        checks.append(_check(
            "conveyor_wire_bytes", cs.get("physical_boundary_bytes", 0.0),
            T * sum(op["wire_bytes"] for op in cp)))
        if getattr(strategy, "pipe_shard", False):
            rs = _ops(manifest, "reduce-scatter")
            ag = [op for op in _ops(manifest, "all-gather")
                  if op["dtype"] == "f32"]
            checks.append(_check(
                "rs_wire_bytes", cs["physical_reduce_scatter_bytes"],
                sum(op["wire_bytes"] for op in rs)))
            checks.append(_check(
                "ag_wire_bytes", cs["physical_all_gather_bytes"],
                sum(op["wire_bytes"] for op in ag)))
        elif dp > 1:
            ar = _ops(manifest, "all-reduce", scalar=False)
            classes = {cs["gp_grad_row_bytes"], cs["gp_state_row_bytes"]}
            for op in ar:
                if op["payload_bytes"] not in classes:
                    res["unexplained"].append(op)
            checks.append(_check(
                "grad_state_wire_bytes", cs["physical_allreduce_bytes"],
                sum(op["wire_bytes"] for op in ar)))

    elif name == "TPGPipeStrategy":
        itemsize = strategy.compute_dtype.itemsize
        S, dp, tp = strategy.num_stages, strategy.dp, strategy.tp
        M = strategy.num_microbatches
        T = M + S - 1
        cp = _ops(manifest, "collective-permute")
        act = float(strategy._act_size) * itemsize
        checks.append(_check("cp_op_count", 2, len(cp)))
        for op in cp:
            checks.append(_check(
                f"cp_payload[{op['name']}]", act, op["payload_bytes"]))
            checks.append(_check(
                f"cp_pairs[{op['name']}]", (S - 1) * dp * tp,
                op["n_pairs"]))
        checks.append(_check(
            "conveyor_wire_bytes", cs.get("physical_boundary_bytes", 0.0),
            T * sum(op["wire_bytes"] for op in cp)))
        # every nonscalar all-reduce must land in one analytic payload
        # class, keyed by (mesh axes, per-participant payload)
        classes = {
            ("model", cs["tp_psum_payload_bytes"]): "tp_psum",
            ("data", cs["tp_grad_sliced_row_bytes"]): "grad_sliced",
            ("data+model", cs["tp_grad_repl_row_bytes"]): "grad_repl",
            ("data", cs["tp_state_row_bytes"]): "state",
            ("model", cs["tp_state_row_bytes"]): "state",
        }
        grad_state_wire = 0.0
        n_psum = 0
        for op in _ops(manifest, "all-reduce", scalar=False):
            key = (op.get("axes"), op["payload_bytes"])
            label = classes.get(key)
            if label is None:
                res["unexplained"].append(op)
            elif label == "tp_psum":
                n_psum += 1
            else:
                grad_state_wire += op["wire_bytes"]
        res["tp_psum_ops"] = n_psum
        checks.append(_check(
            "grad_state_wire_bytes", cs["physical_allreduce_bytes"],
            grad_state_wire))

    else:
        res["tieable"] = False

    res["ok"] = (res["tieable"] and not res["unexplained"]
                 and all(c["ok"] for c in checks))
    return res


# ---------------------------------------------------------------------------
# serve KV-pool tie-out
# ---------------------------------------------------------------------------


def serve_pool_audit(engine) -> Dict[str, Any]:
    """Tie ``pool_page_bytes`` to the actual KV-pool buffers the compiled
    serve programs take as (donated) arguments: the pool_k/pool_v payload
    leaves must equal ``pages * pool_page_bytes`` exactly per layer and in
    total (scale sidecars and the kv_seed scalar split out, never
    counted), and an int8 pool reports exactly f32/4 per element —
    the invariant the handoff wire accounting inherits."""
    import math as _math

    from ddlbench_tpu.ops.paged_decode import pool_page_bytes

    page_axis = engine._page_axis
    n_pages = int(engine.cfg.pool_pages)
    per_page = 0.0
    per_page_f32 = 0.0
    payload, sidecar = 0.0, 0.0
    checks: List[Dict[str, Any]] = []
    for li, pool in enumerate(engine.pools):
        if pool is None:
            continue
        layer_page = float(pool_page_bytes(pool, page_axis))
        per_page += layer_page
        layer_payload = 0.0
        for key, leaf in sorted(pool.items()):
            nbytes = float(_math.prod(leaf.shape) * leaf.dtype.itemsize)
            if key in ("pool_k", "pool_v"):
                layer_payload += nbytes
                per_page_f32 += (4.0 * _math.prod(leaf.shape)
                                 / leaf.shape[page_axis])
            elif key != "kv_seed":
                sidecar += nbytes
        payload += layer_payload
        checks.append(_check(
            f"layer[{li}]_payload_bytes", layer_page * n_pages,
            layer_payload))
    checks.append(_check("pool_page_bytes", per_page,
                         float(engine.bytes_per_page)))
    checks.append(_check("pool_payload_bytes", per_page * n_pages,
                         payload))
    import jax.numpy as jnp
    if engine.dtype == jnp.int8:
        checks.append(_check("int8_page_is_f32_quarter",
                             per_page_f32 / 4.0, per_page))
    # SDC checksum sidecar (serve/integrity.py): when the ledger is armed
    # the handoff wire ships one CHECKSUM_BYTES word per (pool layer,
    # page) next to payload + scale sidecars — tie this audit's own pool
    # walk against integrity's notion of the checksum domain, the exact
    # per-page constant behind the fleet's shipped_checksum_bytes.
    from ddlbench_tpu.serve.integrity import CHECKSUM_BYTES, pool_layers
    integrity_on = getattr(engine, "integrity", None) is not None
    pooled_layers = sum(1 for pool in engine.pools if pool is not None)
    checksum_page = float(CHECKSUM_BYTES * pooled_layers
                          if integrity_on else 0)
    if integrity_on:
        checks.append(_check(
            "checksum_bytes_per_page",
            float(CHECKSUM_BYTES * len(pool_layers(engine))),
            checksum_page))
    res = {
        "kv_dtype": str(engine.cfg.kv_dtype),
        "tp": int(engine.cfg.tp),
        "page_axis": page_axis,
        "pool_page_bytes": per_page,
        "n_pages": n_pages,
        "payload_bytes": payload,
        "sidecar_bytes": sidecar,
        "integrity": integrity_on,
        "checksum_bytes_per_page": checksum_page,
        "checks": checks,
        "ok": all(c["ok"] for c in checks),
    }
    return res


# ---------------------------------------------------------------------------
# planner HBM audit
# ---------------------------------------------------------------------------


def planner_stage_hbm_audit(candidate_record: Dict[str, Any],
                            manifest: Dict[str, Any],
                            world: int) -> Optional[Dict[str, Any]]:
    """Signed per-stage error of the planner's HBM model vs the compiled
    program's ``memory_analysis()``.

    The measured side is the per-chip live-byte estimate
    ``(argument + output + temp - alias) / world`` — memory_analysis
    aggregates over the executable's devices, and uniform pipelines place
    one stage column per chip, so each stage's prediction is compared
    against the same per-chip measurement (the planner's stage_mem IS a
    per-chip number). Returns None when memory_analysis is unavailable
    or the candidate carries no per-stage predictions.
    """
    mem = manifest.get("memory")
    stage_mem = candidate_record.get("stage_mem")
    if not mem or mem.get("peak_bytes") is None or not stage_mem:
        return None
    chip = mem["peak_bytes"] / max(world, 1)
    stages = []
    for i, pred in enumerate(stage_mem):
        err = float(pred) - chip
        stages.append({
            "stage": i,
            "predicted_bytes": float(pred),
            "measured_chip_bytes": chip,
            "err_bytes": err,
            "err_frac": err / chip if chip else None,
        })
    return {
        "world": world,
        "measured": mem,
        "measured_chip_bytes": chip,
        "predicted_peak_bytes": float(max(stage_mem)),
        "stages": stages,
    }


def audit_train_config(cfg, name: Optional[str] = None
                       ) -> Tuple[Dict[str, Any], Any]:
    """Build ``cfg``'s registry strategy, AOT-lower one train step on a
    synthetic batch (lowering never executes — donation-safe), and return
    ``(manifest, strategy)`` with the comm_stats reconcile attached under
    ``manifest["reconcile"]``."""
    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.parallel.api import make_strategy

    strategy = make_strategy(cfg)
    data = make_synthetic(cfg.dataset(), cfg.global_batch(),
                          steps_per_epoch=1)
    ts = strategy.init(jax.random.key(cfg.seed))
    x, y = data.batch(0, 0)
    xs, ys = strategy.shard_batch(x, y)
    lr = jnp.float32(cfg.resolved_lr())
    jit_step = (getattr(strategy, "_jit_train_step", None)
                or strategy.train_step)
    man = lower_manifest(
        jit_step, (ts, xs, ys, lr), name or f"train/{cfg.strategy}",
        mesh=getattr(strategy, "mesh", None))
    man["reconcile"] = reconcile_train(strategy, man)
    return man, strategy


def audit_serve_engine(engine, prefix: str = "serve"
                       ) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """Manifests for the engine's jitted serve programs plus the KV-pool
    tie-out. The pool audit rides each manifest under ``pool_audit`` and
    is also returned separately."""
    mesh = getattr(engine, "_mesh", None)
    pool = serve_pool_audit(engine)
    mans = []
    for name, fn, args in engine.audit_programs():
        mans.append(lower_manifest(fn, args, f"{prefix}/{name}",
                                   mesh=mesh, extra={"pool_audit": pool}))
    return mans, pool


def record_hbm_audit(cfg, hbm_audit: Dict[str, Any]) -> Optional[str]:
    """Merge an hbm audit under ``plan_auto["hbm_audit"]`` in the run's
    partition.json (the planner-decision idiom — atomic tmp+replace).
    Returns the path written, or None when there is no persisted plan to
    annotate (no checkpoint_dir / no plan_auto record)."""
    from ddlbench_tpu.parallel.api import _plan_path

    path = _plan_path(cfg)
    if path is None or not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    rec = doc.get("plan_auto")
    if not isinstance(rec, dict):
        return None
    rec["hbm_audit"] = hbm_audit
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=float)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# manifest IO + regression diff
# ---------------------------------------------------------------------------


def write_manifests(path: str, manifests: List[Dict[str, Any]],
                    header: Optional[Dict[str, Any]] = None) -> None:
    """Atomically write an audit ledger: ``{"audit_schema_version",
    ...header, "programs": [...]}``."""
    doc = {"audit_schema_version": AUDIT_SCHEMA_VERSION,
           **(header or {}), "programs": manifests}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True, default=float)
        f.write("\n")
    os.replace(tmp, path)


def load_manifests(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


# Relative growth above which a metric is flagged. flops/HBM from
# cost/memory analysis are deterministic per jaxlib, but tiny layout
# deltas across versions are not regressions — the gate is for the
# unexplained 2x, not the 0.1% assembler burp.
DIFF_TOLERANCE = 0.01


def diff_manifests(old: Dict[str, Any], new: Dict[str, Any],
                   tolerance: float = DIFF_TOLERANCE) -> Dict[str, Any]:
    """Compare two audit ledgers program-by-program. Growth beyond
    ``tolerance`` in flops / bytes-accessed / peak HBM / total wire bytes
    / per-kind collective counts is a regression; programs present only
    in ``new`` are reported as added (not failures), programs that
    disappeared are flagged."""
    def by_name(doc):
        return {p.get("name"): p for p in doc.get("programs", [])}

    a, b = by_name(old), by_name(new)
    regressions: List[Dict[str, Any]] = []
    report: Dict[str, Any] = {
        "added": sorted(set(b) - set(a)),
        "removed": sorted(set(a) - set(b)),
        "regressions": regressions,
        "compared": sorted(set(a) & set(b)),
    }
    for name in report["compared"]:
        pa, pb = a[name], b[name]
        metrics = [
            ("flops", pa.get("flops"), pb.get("flops")),
            ("bytes_accessed", pa.get("bytes_accessed"),
             pb.get("bytes_accessed")),
            ("peak_bytes", (pa.get("memory") or {}).get("peak_bytes"),
             (pb.get("memory") or {}).get("peak_bytes")),
            ("wire_bytes_total", pa.get("wire_bytes_total"),
             pb.get("wire_bytes_total")),
        ]
        for kind in sorted(set(pa.get("collective_totals", {}))
                           | set(pb.get("collective_totals", {}))):
            ca = pa.get("collective_totals", {}).get(kind, {})
            cb = pb.get("collective_totals", {}).get(kind, {})
            metrics.append((f"collectives[{kind}].count",
                            ca.get("count", 0), cb.get("count", 0)))
            metrics.append((f"collectives[{kind}].wire_bytes",
                            ca.get("wire_bytes", 0.0),
                            cb.get("wire_bytes", 0.0)))
        for metric, va, vb in metrics:
            if va is None or vb is None:
                continue
            if vb > va * (1.0 + tolerance) + 1e-9:
                regressions.append({
                    "program": name, "metric": metric,
                    "old": float(va), "new": float(vb),
                    "growth": (vb / va - 1.0) if va else math.inf,
                })
    if report["removed"]:
        for name in report["removed"]:
            regressions.append({"program": name, "metric": "removed",
                                "old": 1.0, "new": 0.0, "growth": -1.0})
    report["ok"] = not regressions
    return report
