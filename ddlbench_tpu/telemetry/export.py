"""Chrome trace-event JSON export — the Perfetto-loadable trace format.

Emits the JSON Object Format of the Trace Event spec (the format
``chrome://tracing`` and https://ui.perfetto.dev load directly):

* one ``"X"`` (complete) event per span with ``ts``/``dur`` in
  MICROSECONDS (float; the spec's unit),
* ``"C"`` counter samples and ``"i"`` instants pass through,
* one ``"M"`` ``thread_name`` metadata event per thread, so the main
  loop, every prefetch producer, and the watchdog each get a named track,
* a top-level ``metadata`` object recording the tracer's drop count (the
  ring keeps the newest window when a run outlives its capacity).

All events share one ``pid`` (this is a single-process host trace; device
timelines come from the ``jax.profiler`` capture next to it, aligned via
``StepTraceAnnotation`` step numbers in the span args).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from ddlbench_tpu.telemetry.tracer import Tracer

_PID = 1  # single host process; one pid keeps Perfetto's track grouping flat


def chrome_trace_dict(tracer: Tracer) -> Dict[str, Any]:
    """Build the trace-event dict (separated from file I/O for tests)."""
    events: List[Dict[str, Any]] = []
    # Track key is (os thread id, thread name), mapped to a synthetic tid:
    # the OS reuses idents of joined threads (each epoch's prefetch
    # producer would otherwise alias the previous one's track).
    track_ids: Dict[tuple, int] = {}
    for phase, name, t0_ns, dur_ns, os_tid, tname, args in tracer.events():
        key = (os_tid, tname)
        tid = track_ids.get(key)
        if tid is None:
            tid = track_ids[key] = len(track_ids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": _PID, "tid": tid,
                "args": {"name": tname},
            })
        evt: Dict[str, Any] = {
            "ph": phase, "name": name, "pid": _PID, "tid": tid,
            "ts": t0_ns / 1e3,
        }
        if phase == "X":
            evt["dur"] = dur_ns / 1e3
        if phase == "i":
            evt["s"] = "t"  # thread-scoped instant
        if args:
            evt["args"] = dict(args)
        events.append(evt)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "producer": "ddlbench_tpu.telemetry",
            "dropped_events": tracer.dropped_events,
        },
    }


def export_chrome_trace(tracer: Tracer, path: str) -> int:
    """Write the trace to ``path``; returns the number of span/counter
    events written (metadata events excluded)."""
    doc = chrome_trace_dict(tracer)
    with open(path, "w") as f:
        json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e["ph"] != "M")
