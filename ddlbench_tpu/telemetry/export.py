"""Chrome trace-event JSON export — the Perfetto-loadable trace format.

Emits the JSON Object Format of the Trace Event spec (the format
``chrome://tracing`` and https://ui.perfetto.dev load directly):

* one ``"X"`` (complete) event per span with ``ts``/``dur`` in
  MICROSECONDS (float; the spec's unit),
* ``"C"`` counter samples and ``"i"`` instants pass through,
* one ``"M"`` ``thread_name`` metadata event per thread, so the main
  loop, every prefetch producer, and the watchdog each get a named track
  (the serving engine's virtual-time events carry synthetic track names
  instead — one track per request per replica, laid out the same way),
* a top-level ``metadata`` object recording the tracer's drop count AND
  ring capacity (the ring keeps the newest window when a run outlives
  its capacity), plus any caller-supplied metadata (servebench embeds
  its SLOs/time unit so ``serveview`` can default from the file).

Truncation discipline: a reducer that silently under-counts on a
truncated trace is worse than no reducer — :func:`trace_truncation`
reads the drop count back out of any trace dict and
:func:`warn_if_truncated` is the shared loud path every CLI reducer
(``overlap``/``bubble``/``serveview``) goes through.

All events share one ``pid`` (this is a single-process host trace; device
timelines come from the ``jax.profiler`` capture next to it, aligned via
``StepTraceAnnotation`` step numbers in the span args).
"""

from __future__ import annotations

import json
import sys
from typing import Any, Dict, List, Optional

from ddlbench_tpu.telemetry.tracer import Tracer

_PID = 1  # single host process; one pid keeps Perfetto's track grouping flat


def _runtime_metadata() -> Dict[str, Any]:
    """jax/jaxlib versions + backend + attached-device count, best-effort
    (the exporter must keep working where jax is absent or not yet
    initialized — e.g. pure-host serve traces in stripped test envs)."""
    out: Dict[str, Any] = {}
    try:
        import jax
        import jaxlib

        out["jax_version"] = jax.__version__
        out["jaxlib_version"] = jaxlib.__version__
        out["backend"] = jax.default_backend()
        out["device_count"] = jax.device_count()
    except Exception:  # pragma: no cover - stripped environments
        pass
    return out


def chrome_trace_dict(tracer: Tracer,
                      extra_metadata: Optional[Dict[str, Any]] = None,
                      ) -> Dict[str, Any]:
    """Build the trace-event dict (separated from file I/O for tests)."""
    events: List[Dict[str, Any]] = []
    # Track key is (os thread id, thread name), mapped to a synthetic tid:
    # the OS reuses idents of joined threads (each epoch's prefetch
    # producer would otherwise alias the previous one's track).
    track_ids: Dict[tuple, int] = {}
    for phase, name, t0_ns, dur_ns, os_tid, tname, args in tracer.events():
        key = (os_tid, tname)
        tid = track_ids.get(key)
        if tid is None:
            tid = track_ids[key] = len(track_ids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": _PID, "tid": tid,
                "args": {"name": tname},
            })
        evt: Dict[str, Any] = {
            "ph": phase, "name": name, "pid": _PID, "tid": tid,
            "ts": t0_ns / 1e3,
        }
        if phase == "X":
            evt["dur"] = dur_ns / 1e3
        if phase == "i":
            evt["s"] = "t"  # thread-scoped instant
        if args:
            evt["args"] = dict(args)
        events.append(evt)
    metadata = {
        "producer": "ddlbench_tpu.telemetry",
        "dropped_events": tracer.dropped_events,
        "capacity": tracer.capacity,
        # runtime provenance: traces and audit manifests (telemetry/
        # audit.py ledgers stamp the same fields) are joinable by run
        **_runtime_metadata(),
    }
    if extra_metadata:
        metadata.update(extra_metadata)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": metadata,
    }


def export_chrome_trace(tracer: Tracer, path: str,
                        extra_metadata: Optional[Dict[str, Any]] = None,
                        ) -> int:
    """Write the trace to ``path``; returns the number of span/counter
    events written (metadata events excluded)."""
    doc = chrome_trace_dict(tracer, extra_metadata)
    with open(path, "w") as f:
        json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e["ph"] != "M")


AUTOSCALE_PREFIX = "autoscale:"


def autoscale_decisions(doc: Any) -> List[Dict[str, Any]]:
    """Pull the autoscaler's decision instants back out of a trace.

    serve/autoscaler.py emits one ``"i"`` instant per actuation
    (``autoscale:scale_up`` / ``:scale_down`` / ``:repair`` /
    ``:budget_exhausted``) on an ``autoscale/<fleet>`` track, with the
    full ledger event — including the triggering signal snapshot — in
    ``args``. This reducer returns them in trace order as
    ``{"t": <model passes>, "kind": ..., **args}``, so "why did the
    fleet resize at t=384?" is answerable from the trace alone.
    Accepts a live Tracer or an exported trace dict/event list."""
    out: List[Dict[str, Any]] = []
    if hasattr(doc, "events"):  # a live telemetry.Tracer
        for phase, name, t0_ns, _dur, _tid, _tname, args in doc.events():
            if phase == "i" and name.startswith(AUTOSCALE_PREFIX):
                out.append({"t": t0_ns / 1e3,
                            "kind": name[len(AUTOSCALE_PREFIX):],
                            **(args or {})})
        return out
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    for e in events:
        name = str(e.get("name", ""))
        if e.get("ph") == "i" and name.startswith(AUTOSCALE_PREFIX):
            # serve traces stamp 1 model pass = 1000 trace-ns, and the
            # exporter writes ts in us — so ts IS virtual model passes
            out.append({"t": float(e.get("ts", 0.0)),
                        "kind": name[len(AUTOSCALE_PREFIX):],
                        **(e.get("args") or {})})
    return out


SDC_PREFIX = "sdc:"


def sdc_events(doc: Any) -> List[Dict[str, Any]]:
    """Pull the SDC defense's detection instants back out of a trace.

    serve/engine.py emits one ``"i"`` instant per ledger event
    (``sdc:detect`` / ``:quarantine``) on a ``<replica>/sdc`` track with
    the slot, trust boundary, and displaced-request count in ``args``.
    Returned in trace order as ``{"t": <model passes>, "kind": ...,
    **args}`` — the same contract as :func:`autoscale_decisions` — so
    "which boundary caught the flip at t=6?" is answerable from the
    trace alone. Accepts a live Tracer or an exported trace dict/list."""
    out: List[Dict[str, Any]] = []
    if hasattr(doc, "events"):  # a live telemetry.Tracer
        for phase, name, t0_ns, _dur, _tid, _tname, args in doc.events():
            if phase == "i" and name.startswith(SDC_PREFIX):
                out.append({"t": t0_ns / 1e3,
                            "kind": name[len(SDC_PREFIX):],
                            **(args or {})})
        return out
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else doc
    for e in events:
        name = str(e.get("name", ""))
        if e.get("ph") == "i" and name.startswith(SDC_PREFIX):
            # serve traces stamp 1 model pass = 1000 trace-ns → ts in us
            # IS virtual model passes (the autoscale_decisions convention)
            out.append({"t": float(e.get("ts", 0.0)),
                        "kind": name[len(SDC_PREFIX):],
                        **(e.get("args") or {})})
    return out


def trace_truncation(doc: Any) -> int:
    """Drop count recorded in a trace's metadata block: > 0 means the ring
    overflowed and the OLDEST events are gone. 0 for bare event lists and
    device traces (no metadata — nothing to claim either way)."""
    if hasattr(doc, "dropped_events"):  # a live telemetry.Tracer
        return int(doc.dropped_events)
    if isinstance(doc, dict):
        meta = doc.get("metadata") or {}
        try:
            return int(meta.get("dropped_events", 0) or 0)
        except (TypeError, ValueError):
            return 0
    return 0


def warn_if_truncated(doc: Any, reducer: str) -> int:
    """Loud stderr banner when ``doc`` is a truncated trace — every CLI
    reducer calls this so a windowed ring can never silently shrink the
    figures it reports. Returns the drop count."""
    n = trace_truncation(doc)
    if n:
        cap = ""
        if isinstance(doc, dict):
            c = (doc.get("metadata") or {}).get("capacity")
            cap = f" (ring capacity {c})" if c else ""
        print(f"{reducer}: WARNING: trace is TRUNCATED — {n} oldest events "
              f"were dropped by the ring buffer{cap}; reduced figures "
              "under-count the run. Re-capture with a larger "
              "--trace-capacity.", file=sys.stderr, flush=True)
    return n
