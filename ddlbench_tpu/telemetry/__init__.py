"""Step-level telemetry: unified host/device tracing + latency percentiles.

The reference's observability is print-scraped throughput lines (SURVEY.md
§5.5) — they say *that* a strategy is slow, never *where* the time went.
This package is the decomposition layer the ROADMAP north star needs:

* :mod:`telemetry.tracer` — a thread-safe, ring-buffered span/counter
  tracer on monotonic clocks. Disabled (the default) it is a single
  attribute check returning a cached no-op context manager, so the hot
  loop pays nothing; enabled, every producer/consumer/watchdog thread
  records into one bounded buffer.
* :mod:`telemetry.export` — Chrome trace-event JSON (``traceEvents``)
  loadable in Perfetto / ``chrome://tracing``: one track per thread (main
  loop, prefetch producer, watchdog), named via ``thread_name`` metadata
  events.
* :mod:`telemetry.stats` — step-latency aggregation: p50/p95/p99/max per
  epoch plus explicit warmup/compile-time accounting, feeding the epoch
  log lines, JSONL, ``summary()``, and ``bench.py`` JSON.
* :mod:`telemetry.serveview` — the serving-side reducer: request-
  lifecycle traces (serve/engine.py under ``ServeConfig.trace``, stamped
  in virtual model-pass units on one track per request per replica)
  reduce to exact TTFT queue/prefill/decode/sched-gap decompositions,
  ITL decode/preempted splits, and the windowed SLO-attainment + goodput
  time series ROADMAP item 2c's autoscaler consumes.
* :mod:`telemetry.audit` — the compiled-program audit plane: per-program
  manifests (flops / HBM components / the per-collective ledger walked
  out of the optimized HLO) cross-checked EXACTLY against the analytic
  models (``comm_stats`` wire bytes, the planner's HBM model,
  ``pool_page_bytes``), plus the ``auditbench diff`` regression gate.

Host spans align with device traces through
``jax.profiler.StepTraceAnnotation`` wrapping in ``train/loop.py`` and the
windowed ``--xla-trace-steps A:B`` capture next to ``--trace-dir``
(ddlbench_tpu/cli.py).

Telemetry is metrics-neutral by construction: it only reads clocks, so
losses are bitwise identical with tracing on or off (pinned by
tests/test_telemetry.py).
"""

from ddlbench_tpu.telemetry.tracer import (  # noqa: F401
    Tracer,
    get_tracer,
    set_tracer,
)
from ddlbench_tpu.telemetry.export import (  # noqa: F401
    export_chrome_trace,
    trace_truncation,
    warn_if_truncated,
)
from ddlbench_tpu.telemetry.audit import (  # noqa: F401
    AUDIT_SCHEMA_VERSION,
    CollectiveOp,
    collective_ledger,
    diff_manifests,
    lower_manifest,
    program_manifest,
    reconcile_train,
    serve_pool_audit,
)
from ddlbench_tpu.telemetry.overlap import overlap_fraction  # noqa: F401
from ddlbench_tpu.telemetry.bubble import bubble_fraction  # noqa: F401
from ddlbench_tpu.telemetry.serveview import breakdown  # noqa: F401
from ddlbench_tpu.telemetry.stats import (  # noqa: F401
    StepLatencyStats,
    percentile,
    request_slo_ok,
    serve_summary,
)
