"""Comm/compute overlap-fraction reducer over Chrome-trace files.

The point of the bucketed dp engine (``--comm-buckets``, parallel/dp.py) is
that collective wire time hides under compute. This module turns a trace
into the single number that says whether it actually did: the fraction of
total COMMUNICATION span time that ran concurrently with at least one
COMPUTE span::

    overlap_fraction = |union(comm) ∩ union(compute)| / |union(comm)|

Works on any trace in the Chrome trace-event JSON format:

* the ``--trace`` host span trace (telemetry/export.py) — comm spans are
  the engine's ``rs_bucket``/``ag_bucket``/``ar_bucket`` markers (exact
  wire-byte accounting, near-zero host duration: they mark the SCHEDULE,
  so host-trace overlap is not a device measurement),
* an XLA device trace exported from ``--trace-dir`` via Perfetto/TensorBoard
  — comm spans are the async collective ops (``all-reduce``,
  ``reduce-scatter``, ``all-gather``, ...), compute spans the fusions; the
  overlap fraction THERE is the real measurement the round-9 A/B reports.

Spans are classified by name prefix (case-insensitive), and intervals are
unioned ACROSS tracks before intersecting — an async collective on a
separate stream track overlapping a fusion on the compute track is
precisely the signal. Container spans that would blanket the timeline
(``dp_explicit_update``, ``train_step``, epochs) are excluded from the
default compute set by prefix denylist.

CLI::

    python -m ddlbench_tpu.telemetry.overlap trace.json \
        [--comm rs_bucket,ag_bucket] [--compute fusion,dot,conv]
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Default comm-span prefixes: the dp engine's bucket markers plus the op
# names XLA device traces use for collectives.
COMM_PREFIXES = (
    "rs_bucket", "ag_bucket", "ar_bucket",
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "psum", "ppermute", "send", "recv",
)

# Host-trace container/bookkeeping spans that span the whole step and must
# not count as "compute running under the collective".
CONTAINER_PREFIXES = (
    "dp_explicit_update", "train_step", "epoch", "run", "warmup",
    "checkpoint", "eval", "prefetch_wait", "sync",
)


def _matches(name: str, prefixes: Sequence[str]) -> bool:
    low = name.lower()
    return any(low.startswith(p.lower()) for p in prefixes)


def _merge(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Union of intervals as a sorted disjoint list."""
    out: List[Tuple[float, float]] = []
    for a, b in sorted(i for i in intervals if i[1] > i[0]):
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _total(merged: List[Tuple[float, float]]) -> float:
    return sum(b - a for a, b in merged)


def _intersection(a: List[Tuple[float, float]],
                  b: List[Tuple[float, float]]) -> float:
    """Total length of the intersection of two DISJOINT sorted lists."""
    i = j = 0
    acc = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            acc += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return acc


def _iter_complete_events(doc: Any) -> Iterable[Dict[str, Any]]:
    """'X' (complete) events from a trace dict, event list, or Tracer."""
    if hasattr(doc, "events"):  # a live telemetry.Tracer
        from ddlbench_tpu.telemetry.export import chrome_trace_dict

        doc = chrome_trace_dict(doc)
    events = doc.get("traceEvents", doc) if isinstance(doc, dict) else doc
    for e in events:
        if isinstance(e, dict) and e.get("ph") == "X" \
                and "ts" in e and "dur" in e:
            yield e


def overlap_fraction(trace: Any,
                     comm_prefixes: Sequence[str] = COMM_PREFIXES,
                     compute_prefixes: Optional[Sequence[str]] = None,
                     ) -> Dict[str, Any]:
    """Reduce a trace to its comm/compute overlap figures.

    ``trace``: a Chrome trace dict (``{"traceEvents": [...]}``), a bare
    event list, or a live Tracer. ``compute_prefixes`` None means "every
    complete span that is neither comm nor a container". Returns a dict
    with total/overlapped comm seconds, the overlap fraction (0 when no
    comm spans exist), span counts, and summed ``wire_bytes`` args per
    comm span name (the engine's markers carry exact byte accounting).
    """
    comm_iv: List[Tuple[float, float]] = []
    compute_iv: List[Tuple[float, float]] = []
    comm_spans = compute_spans = 0
    wire_bytes: Dict[str, float] = {}
    for e in _iter_complete_events(trace):
        name = str(e.get("name", ""))
        t0 = float(e["ts"])
        t1 = t0 + float(e["dur"])
        if _matches(name, comm_prefixes):
            comm_iv.append((t0, t1))
            comm_spans += 1
            args = e.get("args") or {}
            if "wire_bytes" in args:
                wire_bytes[name] = (wire_bytes.get(name, 0.0)
                                    + float(args["wire_bytes"]))
        elif compute_prefixes is not None:
            if _matches(name, compute_prefixes):
                compute_iv.append((t0, t1))
                compute_spans += 1
        elif not _matches(name, CONTAINER_PREFIXES):
            compute_iv.append((t0, t1))
            compute_spans += 1
    comm = _merge(comm_iv)
    compute = _merge(compute_iv)
    comm_us = _total(comm)
    overlapped_us = _intersection(comm, compute)
    from ddlbench_tpu.telemetry.export import trace_truncation

    return {
        "comm_s": comm_us / 1e6,  # trace ts/dur are microseconds
        "overlapped_s": overlapped_us / 1e6,
        "overlap_fraction": (overlapped_us / comm_us) if comm_us else 0.0,
        "comm_spans": comm_spans,
        "compute_spans": compute_spans,
        "wire_bytes": wire_bytes,
        # > 0 = the ring dropped events: the fractions under-count
        "dropped_events": trace_truncation(trace),
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="overlap", description=__doc__)
    p.add_argument("trace", help="Chrome trace-event JSON file "
                                 "(--trace output or an exported XLA trace)")
    p.add_argument("--comm", default=None,
                   help="comma list of comm span-name prefixes "
                        f"(default: {','.join(COMM_PREFIXES[:4])},...)")
    p.add_argument("--compute", default=None,
                   help="comma list of compute span-name prefixes "
                        "(default: every non-comm, non-container span)")
    args = p.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    from ddlbench_tpu.telemetry.export import warn_if_truncated

    warn_if_truncated(doc, "overlap")
    comm = (tuple(s for s in args.comm.split(",") if s) if args.comm
            else COMM_PREFIXES)
    compute = (tuple(s for s in args.compute.split(",") if s)
               if args.compute else None)
    print(json.dumps(overlap_fraction(doc, comm, compute)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
