"""Thread-safe, ring-buffered span/counter tracer on monotonic clocks.

Overhead contract (pinned by tests/test_telemetry.py):

* **Disabled** (the default): ``tracer.span(...)`` is one attribute check
  returning a cached no-op context manager; nothing is allocated, nothing
  is locked, no clock is read. Hot loops that cannot even afford the
  kwargs dict guard on ``tracer.enabled`` and call :meth:`Tracer.complete`
  with timestamps they already took for other reasons (the step-latency
  percentiles need them regardless).
* **Enabled**: two ``time.perf_counter_ns`` reads per span plus one
  lock-guarded append into a bounded ``deque``. The ring drops the OLDEST
  events when full (``dropped_events`` counts them), so a long run can
  always be traced — you get the most recent window.

Timestamps are ``time.perf_counter_ns()`` — monotonic, never wall clock —
so spans from different threads order correctly on one timeline and a
host NTP step can never fold the trace. All threads (main loop, prefetch
producer, watchdog) share one tracer; each event records its thread id
and name so the exporter can lay out one track per thread.

Determinism: recording never reorders or perturbs the traced computation
— the tracer only reads clocks — which is what makes the tracing-on/off
bitwise-loss pin possible.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# Event tuples: (phase, name, t0_ns, dur_ns, thread_id, thread_name, args).
# phase follows the Chrome trace-event phases the exporter emits:
# "X" = complete span, "C" = counter sample, "i" = instant.
Event = Tuple[str, str, int, int, int, str, Optional[Dict[str, Any]]]


class _NullSpan:
    """Cached do-nothing context manager — the entire disabled-path cost."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Live span: clocks its own enter/exit and records on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer.complete(self._name, self._t0, time.perf_counter_ns(),
                              self._args)
        return False


class Tracer:
    """Bounded, thread-safe event recorder. One instance serves all threads.

    ``capacity`` bounds host memory: at ~120 bytes/event the default
    200k-event ring tops out around 25 MB regardless of run length.
    """

    def __init__(self, capacity: int = 200_000):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.enabled = False
        self._capacity = capacity
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dropped = 0

    # ---- recording ----

    def span(self, name: str, **args: Any):
        """Context manager timing a region; no-op singleton when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def complete(self, name: str, t0_ns: int, t1_ns: int,
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record an already-timed region (both stamps from
        ``time.perf_counter_ns``). Callers on hot paths guard with
        ``tracer.enabled`` so the disabled path never reaches here."""
        if not self.enabled:
            return
        th = threading.current_thread()
        self._append(("X", name, t0_ns, t1_ns - t0_ns, th.ident or 0,
                      th.name, args))

    def counter(self, name: str, value: float) -> None:
        """Record one sample of a named counter track (e.g. ring depth)."""
        if not self.enabled:
            return
        th = threading.current_thread()
        self._append(("C", name, time.perf_counter_ns(), 0, th.ident or 0,
                      th.name, {"value": value}))

    def instant(self, name: str, **args: Any) -> None:
        """Record a zero-duration marker (e.g. watchdog kick, epoch edge)."""
        if not self.enabled:
            return
        th = threading.current_thread()
        self._append(("i", name, time.perf_counter_ns(), 0, th.ident or 0,
                      th.name, args or None))

    def emit(self, phase: str, name: str, t0_ns: int, dur_ns: int = 0,
             track: str = "virtual",
             args: Optional[Dict[str, Any]] = None) -> None:
        """Record an event on a named SYNTHETIC track with caller-supplied
        timestamps — the virtual-time entry point. The serving engine
        stamps request-lifecycle events in model-pass units scaled by
        1000, so one virtual unit renders as 1 µs in the exported trace
        and every timestamp stays an exact integer (serveview's TTFT
        decomposition tiles without float drift). Synthetic tracks use
        thread id 0, which no started thread carries, so they can never
        alias a real thread's track in the exporter."""
        if not self.enabled:
            return
        self._append((phase, name, int(t0_ns), int(dur_ns), 0, track, args))

    def _append(self, evt: Event) -> None:
        with self._lock:
            if len(self._events) == self._capacity:
                self._dropped += 1
            self._events.append(evt)

    # ---- lifecycle / readout ----

    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    @property
    def capacity(self) -> int:
        """Ring size — exported in the trace metadata so reducers can say
        how big a --trace-capacity would have kept everything."""
        return self._capacity

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def events(self) -> List[Event]:
        """Snapshot of the recorded events in record order."""
        with self._lock:
            return list(self._events)

    @property
    def dropped_events(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# Process-global tracer: instrumentation sites (train/loop.py,
# data/prefetch.py, bench.py) grab it once; the CLI enables it when
# --trace is passed. A plain module global, not a context var — producer
# threads must see the same instance as the loop that spawned them.
_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (tests install bounded fresh ones)."""
    global _TRACER
    _TRACER = tracer
    return tracer
