"""Step-latency aggregation: per-epoch percentiles + warmup accounting.

Wall-clock percentiles over per-step host times answer the first
observability question — is the step-time distribution tight (compute
bound, healthy pipeline) or heavy-tailed (input stalls, periodic syncs,
recompiles)? The loop records EVERY step's wall time (two monotonic clock
reads — cheap enough to stay on even when tracing is off), and the
per-epoch p50/p95/p99/max land on the epoch log line, in JSONL, in
``summary()``, and in ``bench.py`` JSON.

Interpretation note (documented in ARCHITECTURE.md): with async dispatch
and on-device metric accumulation the host loop runs ahead of the device,
so most steps measure DISPATCH cost and the interval-boundary steps absorb
the accumulated device time — a tight p50 with a p95 near
``log_interval x`` the true step time is the signature of a healthy
pipelined loop, not a stutter. The armed watchdog (per-step sync) makes
every sample a true device-step latency.

Warmup/compile accounting is explicit: XLA's first-compile seconds are
clocked separately (``warmup_compile_s``) and NEVER mixed into the step
distribution, so percentiles describe steady-state only.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional


def percentile(samples: List[float], q: float) -> float:
    """q-th percentile (0..100) with linear interpolation (numpy default).

    Pure-Python on sorted copies — sample counts here are steps/epoch
    (thousands at most), far below where numpy would matter.
    """
    if not samples:
        return 0.0
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q={q} outside [0, 100]")
    s = sorted(samples)
    k = (len(s) - 1) * (q / 100.0)
    lo = math.floor(k)
    hi = math.ceil(k)
    if lo == hi:
        return s[int(k)]
    return s[lo] + (s[hi] - s[lo]) * (k - lo)


def latency_summary(samples_s: List[float]) -> Dict[str, float]:
    """p50/p95/p99/max (milliseconds) + count for one sample set."""
    ms = [t * 1e3 for t in samples_s]
    return {
        "p50_ms": percentile(ms, 50.0),
        "p95_ms": percentile(ms, 95.0),
        "p99_ms": percentile(ms, 99.0),
        "max_ms": max(ms) if ms else 0.0,
        "steps": len(ms),
    }


def request_slo_ok(rec: Dict, slo_ttft: Optional[float] = None,
                   slo_itl: Optional[float] = None) -> bool:
    """One finished record's SLO verdict: TTFT <= slo_ttft AND mean ITL
    (TPOT) <= slo_itl; an omitted SLO always passes. One home for the
    predicate — serve_summary's goodput, the engine's
    ``snapshot()['slo_attainment']``, and serveview's windowed attainment
    must never disagree on what "met the SLO" means. ``arrival`` may be
    None (a request submitted without a stamp — the engine treats that as
    time 0 everywhere else, so the predicate does too)."""
    arrival = rec["arrival"]
    ttft = rec["first_token_t"] - (arrival if arrival is not None else 0.0)
    times = rec["token_times"]
    gaps = [b - a for a, b in zip(times, times[1:])]
    tpot = sum(gaps) / len(gaps) if gaps else 0.0
    return ((slo_ttft is None or ttft <= slo_ttft)
            and (slo_itl is None or tpot <= slo_itl))


def serve_summary(records: List[Dict], *, duration: float,
                  slo_ttft: Optional[float] = None,
                  slo_itl: Optional[float] = None,
                  per_tier: bool = False) -> Dict[str, float]:
    """Serving-side latency/goodput aggregation over completed requests.

    ``records`` are the engine's ``finished`` entries
    (serve/engine.py: arrival, first_token_t, token_times, n_tokens).
    Times are in whatever unit the caller measured — the engine's virtual
    model-pass units by default — and the SLOs are in the same unit.

    Reported through the same percentile machinery as the training step
    stats: TTFT (arrival -> first token) and ITL (gap between consecutive
    tokens of one request, pooled over all requests) p50/p95/p99, plus the
    serving headline — **goodput under SLO**: output tokens per time unit
    counting ONLY requests that met BOTH SLOs (:func:`request_slo_ok`).
    Throughput counts every completed token; the goodput/throughput gap
    is the capacity wasted on requests served too late to matter.

    Degenerate inputs are schema-stable by contract: zero finished
    requests and/or zero duration (a run that admitted nothing, a
    snapshot taken at t=0) return the SAME key set with all-zero values —
    never a ZeroDivisionError, never a dropped field (consumers scrape
    these keys; tests/test_telemetry.py pins the edge paths).

    ``per_tier=True`` (the SLO-tier split, ISSUE 15) additionally reports
    ``{interactive,batch}_{completed, output_tokens, ttft_p50, ttft_p95,
    itl_p50, slo_attainment, goodput_tokens_per_unit}`` — the same
    definitions restricted to each tier's records (a record without a
    ``tier`` field counts as interactive, the engine's default). The keys
    are FLAG-GATED by this parameter so plain callers keep the pinned
    schema; both tiers always appear (zeroes for an absent tier) so the
    flagged schema is stable too.
    """
    ttfts, itls, good_tokens, total_tokens, n_ok = [], [], 0, 0, 0
    # per-tier buckets fill in the SAME pass so the metric definitions
    # (ttft, gap, SLO verdict, goodput) exist exactly once
    by_tier = {t: {"ttft": [], "itl": [], "completed": 0, "tokens": 0,
                   "ok": 0, "good": 0} for t in ("interactive", "batch")}
    for r in records:
        arrival = r["arrival"]
        ttft = r["first_token_t"] - (arrival if arrival is not None
                                     else 0.0)
        times = r["token_times"]
        gaps = [b - a for a, b in zip(times, times[1:])]
        ok = request_slo_ok(r, slo_ttft, slo_itl)
        ttfts.append(ttft)
        itls.extend(gaps)
        total_tokens += r["n_tokens"]
        if ok:
            n_ok += 1
            good_tokens += r["n_tokens"]
        if per_tier:
            b = by_tier.get(r.get("tier", "interactive"))
            if b is not None:  # unknown tier labels fall in no bucket
                b["ttft"].append(ttft)
                b["itl"].extend(gaps)
                b["completed"] += 1
                b["tokens"] += r["n_tokens"]
                if ok:
                    b["ok"] += 1
                    b["good"] += r["n_tokens"]
    out = {
        "completed": len(records),
        "output_tokens": total_tokens,
        "duration": duration,
        # zero-duration guard: rates are 0, not a divide blow-up
        "throughput_tokens_per_unit": (total_tokens / duration
                                       if duration > 0 else 0.0),
        "goodput_tokens_per_unit": (good_tokens / duration
                                    if duration > 0 else 0.0),
        "slo_attainment": n_ok / len(records) if records else 0.0,
        # prompt tokens served from the cross-request prefix cache
        # (serve/prefix.py) over all completed requests — 0 with the cache
        # off or on the static baseline, keeping the schema stable
        "prefix_cached_tokens": sum(
            r.get("cached_tokens", 0) for r in records),
    }
    for name, samples in (("ttft", ttfts), ("itl", itls)):
        for q in (50.0, 95.0, 99.0):
            out[f"{name}_p{q:.0f}"] = percentile(samples, q)
    if slo_ttft is not None:
        out["slo_ttft"] = slo_ttft
    if slo_itl is not None:
        out["slo_itl"] = slo_itl
    if per_tier:
        for tier, b in by_tier.items():
            out[f"{tier}_completed"] = b["completed"]
            out[f"{tier}_output_tokens"] = b["tokens"]
            out[f"{tier}_ttft_p50"] = percentile(b["ttft"], 50.0)
            out[f"{tier}_ttft_p95"] = percentile(b["ttft"], 95.0)
            out[f"{tier}_itl_p50"] = percentile(b["itl"], 50.0)
            out[f"{tier}_slo_attainment"] = (
                b["ok"] / b["completed"] if b["completed"] else 0.0)
            out[f"{tier}_goodput_tokens_per_unit"] = (
                b["good"] / duration if duration > 0 else 0.0)
    return out


class StepLatencyStats:
    """Per-epoch step-duration collector for one run (single-threaded:
    only the train loop records)."""

    def __init__(self) -> None:
        self._epochs: Dict[int, List[float]] = {}
        self.warmup_compile_s: Optional[float] = None

    def record_step(self, epoch: int, seconds: float) -> None:
        self._epochs.setdefault(epoch, []).append(seconds)

    def set_warmup(self, seconds: float) -> None:
        """Clock the out-of-band warmup/compile block (train/loop.py runs
        it on a throwaway state before the measured epochs)."""
        self.warmup_compile_s = seconds

    def epoch_summary(self, epoch: int) -> Optional[Dict[str, float]]:
        samples = self._epochs.get(epoch)
        if not samples:
            return None
        return latency_summary(samples)

    def run_summary(self) -> Optional[Dict[str, float]]:
        """Percentiles over ALL recorded steps (not a mean of per-epoch
        percentiles), plus the warmup/compile accounting."""
        samples = [t for ep in sorted(self._epochs) for t in self._epochs[ep]]
        if not samples:
            return None
        out = latency_summary(samples)
        if self.warmup_compile_s is not None:
            out["warmup_compile_s"] = self.warmup_compile_s
        return out
