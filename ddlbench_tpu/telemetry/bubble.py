"""Pipeline bubble-fraction reducer over Chrome-trace files.

The point of a pipeline schedule (``--pipe-schedule``, parallel/
pipeline_rt.py) is a smaller bubble: the fraction of device time the stage
ring sits idle between useful tick events. This module turns a trace into
that number::

    bubble_fraction = sum_over_stages(window - union(tick spans))
                      / (num_stages * window)

mirroring telemetry/overlap.py's interval machinery: works on any trace in
the Chrome trace-event JSON format —

* the ``--trace`` host span trace (telemetry/export.py): the runtime emits
  per-stage ``pipe_tick`` marker spans (:func:`emit_tick_spans`) that
  project the step's TIMETABLE onto the measured step window, one span per
  busy half-tick per stage, with ``args = {stage, chunk, mb, event,
  half_tick, step}``. The reduced fraction is the SCHEDULE's bubble — the
  analytic quantity partition/schedule.py predicts
  (Timetable.bubble_fraction), pinned to agree within 10% on the synthetic
  fixture by the ``pipesched`` suite;
* an XLA device trace exported from ``--trace-dir`` via Perfetto/
  TensorBoard: pass ``--spans fusion,dot,conv,...`` (or any op-name
  prefixes) and group tracks by tid — the measured fraction THERE is the
  real device bubble the round-10 A/B reports.

Stages are identified by the span's ``stage`` arg when present (host
marker spans all share one thread track), else by the trace ``tid``
(device traces put each core on its own track). The window defaults to the
GLOBAL [earliest start, latest end] across all matched spans — leading and
trailing fill/drain idle counts, exactly as in the analytic fraction; pass
``per_stage_window=True`` to measure each stage against its own extent
instead (drops the fill/drain skew, useful on raggedy device traces).

CLI::

    python -m ddlbench_tpu.telemetry.bubble trace.json \
        [--spans pipe_tick] [--per-stage-window] [--step N]
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ddlbench_tpu.telemetry.overlap import (_iter_complete_events, _matches,
                                            _merge, _total)

# Default span-name prefixes marking useful pipeline work: the runtime's
# schedule markers plus the tick-span names an annotated device trace uses.
TICK_PREFIXES = ("pipe_tick",)


def emit_tick_spans(tracer, timetable, t0_ns: int, t1_ns: int,
                    step: Optional[int] = None) -> int:
    """Project ``timetable`` onto the measured step window as ``pipe_tick``
    marker spans (one per EVENT per stage, spanning the event's whole
    half-tick cost — for unit-cost tables that is one span per busy
    half-tick, the original behavior; for cost-weighted tables one span
    covers the event's ``cost`` consecutive cells instead of splintering
    into per-cell spans) — the host-trace food for
    :func:`bubble_fraction`. The projection divides [t0_ns, t1_ns) into H
    equal half-ticks; the reduced fraction is timeline-scale invariant, so
    the wall window only sets the display scale. Returns the number of
    spans emitted (0 when the tracer is disabled)."""
    if not getattr(tracer, "enabled", False):
        return 0
    from ddlbench_tpu.partition.schedule import (EVENT_BWD_IN, EVENT_BWD_W,
                                                 EVENT_FWD)

    H = timetable.half_ticks
    S = timetable.num_stages
    tick_ns = max(1, (t1_ns - t0_ns)) / H
    deferred = set(timetable.deferred_w or ())
    n = 0
    for kind in (EVENT_FWD, EVENT_BWD_IN, EVENT_BWD_W):
        for (c, m), h in sorted(timetable.event_times(kind).items()):
            cost = timetable.cost_of(kind, c)
            a = int(t0_ns + h * tick_ns)
            b = int(t0_ns + (h + cost) * tick_ns)
            args = {
                "stage": int(c % S),
                "chunk": int(c),
                "mb": int(m),
                "event": int(kind),
                "half_tick": int(h),
                "schedule": timetable.name,
            }
            if kind == EVENT_BWD_W and (c, m) in deferred:
                # ZB-H2: this W is deferred past the step boundary in the
                # steady-state accounting — trace viewers can see which
                # tail cells overlap the next step's warmup, and the
                # measured single-step fraction explains its gap vs the
                # steady analytic (bubble_is_estimate)
                args["deferred"] = True
            if step is not None:
                args["step"] = step
            tracer.complete("pipe_tick", a, b, args)
            n += 1
    return n


def _track_key(e: Dict[str, Any]) -> Any:
    args = e.get("args") or {}
    if "stage" in args:
        return ("stage", args["stage"])
    return ("tid", e.get("tid"))


def bubble_fraction(trace: Any,
                    span_prefixes: Sequence[str] = TICK_PREFIXES,
                    per_stage_window: bool = False,
                    step: Optional[int] = None) -> Dict[str, Any]:
    """Reduce a trace to its pipeline-bubble figures.

    ``trace``: a Chrome trace dict (``{"traceEvents": [...]}``), a bare
    event list, or a live Tracer. ``step`` filters marker spans to one
    step's projection (spans without a ``step`` arg always match); with
    ``step=None`` and step-tagged spans present, only the LATEST tagged
    step's projection is reduced — a multi-epoch --trace emits one
    projection per epoch, and unioning them against one global window
    would count every inter-epoch gap as bubble. Returns total/idle
    stage-time, the bubble fraction (0 when no spans match), span counts,
    and the per-stage breakdown.
    """
    matched = []
    tagged_steps = set()
    for e in _iter_complete_events(trace):
        if not _matches(str(e.get("name", "")), span_prefixes):
            continue
        args = e.get("args") or {}
        if step is not None and "step" in args and args["step"] != step:
            continue
        if "step" in args:
            tagged_steps.add(args["step"])
        matched.append(e)
    if step is None and tagged_steps:
        latest = max(tagged_steps)
        matched = [e for e in matched
                   if (e.get("args") or {}).get("step", latest) == latest]
    tracks: Dict[Any, List[Tuple[float, float]]] = {}
    spans = 0
    schedule = None
    for e in matched:
        t0 = float(e["ts"])
        tracks.setdefault(_track_key(e), []).append((t0, t0 + float(e["dur"])))
        schedule = (e.get("args") or {}).get("schedule", schedule)
        spans += 1
    from ddlbench_tpu.telemetry.export import trace_truncation

    dropped = trace_truncation(trace)
    merged = {k: _merge(iv) for k, iv in tracks.items()}
    if not merged:
        return {"bubble_fraction": 0.0, "stages": 0, "tick_spans": 0,
                "total_s": 0.0, "idle_s": 0.0, "per_stage": {},
                "schedule": schedule, "dropped_events": dropped}
    lo = min(iv[0][0] for iv in merged.values() if iv)
    hi = max(iv[-1][1] for iv in merged.values() if iv)
    per_stage: Dict[str, float] = {}
    total_us = idle_us = 0.0
    for k, iv in sorted(merged.items(), key=lambda kv: str(kv[0])):
        if per_stage_window and iv:
            w0, w1 = iv[0][0], iv[-1][1]
        else:
            w0, w1 = lo, hi
        window = w1 - w0
        busy = _total(iv)
        total_us += window
        idle_us += window - busy
        per_stage[str(k[1])] = ((window - busy) / window) if window else 0.0
    return {
        "bubble_fraction": (idle_us / total_us) if total_us else 0.0,
        "stages": len(merged),
        "tick_spans": spans,
        "total_s": total_us / 1e6,  # trace ts/dur are microseconds
        "idle_s": idle_us / 1e6,
        "per_stage": per_stage,
        "schedule": schedule,
        # > 0 = the ring dropped events: the fraction under-counts idle
        "dropped_events": dropped,
    }


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="bubble", description=__doc__)
    p.add_argument("trace", help="Chrome trace-event JSON file "
                                 "(--trace output or an exported XLA trace)")
    p.add_argument("--spans", default=None,
                   help="comma list of tick span-name prefixes "
                        f"(default: {','.join(TICK_PREFIXES)}; for device "
                        f"traces try fusion,dot,conv,loop)")
    p.add_argument("--per-stage-window", action="store_true",
                   help="measure each stage against its own first-to-last "
                        "span extent instead of the global window "
                        "(drops fill/drain skew)")
    p.add_argument("--step", type=int, default=None,
                   help="reduce only the marker spans of this step")
    args = p.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    from ddlbench_tpu.telemetry.export import warn_if_truncated

    warn_if_truncated(doc, "bubble")
    prefixes = (tuple(s for s in args.spans.split(",") if s) if args.spans
                else TICK_PREFIXES)
    print(json.dumps(bubble_fraction(doc, prefixes,
                                     per_stage_window=args.per_stage_window,
                                     step=args.step)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
