"""Typed configuration for the whole framework.

The reference stacks four config mechanisms (bash getopts flags, env vars as a
cross-process bus, per-script argparse, and PipeDream's generated JSON confs —
see reference run/run/run.sh:16-47, run/run/run_template.sh:70-73,
benchmark/mnist/mnist_pytorch.py:157-160, optimizer/templates/conf.json.template).
Here there is exactly one: a frozen dataclass, constructible from CLI flags
(see ddlbench_tpu/cli.py) or from a dict.

Hardware cost-model constants (the reference inlines NETWORK_BANDWIDTH=5e9,
PCIE_BANDWIDTH=32e9, MEMORY_SIZE=11e9|24e9 in bash, run_template.sh:414-420)
live in :class:`HardwareModel`, defaulted to TPU v5e numbers, and feed the
pipeline partitioner (ddlbench_tpu/partition/optimizer.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    """Shape/size blueprint of one benchmark dataset.

    Mirrors the synthetic-data factory specs in the reference
    (benchmark/generate_synthetic_data.py:75-107). ``kind`` distinguishes image
    workloads (NHWC float input, one label per sample) from token workloads
    (int sequence input, next-token labels) — the sequence-length benchmark
    axis the reference approximates spatially with "highres" (SURVEY.md §5.7).
    """

    name: str
    image_size: Tuple[int, ...]  # (H, W, C) for images; (T,) for tokens
    num_classes: int  # classes, or vocab size for tokens
    train_size: int
    test_size: int
    kind: str = "image"  # "image" | "tokens" | "seq2seq"
    # seq2seq only: length of the source segment within the T-token stream
    # (positions < src_len are the source; loss is masked there).
    src_len: Optional[int] = None

    def __post_init__(self) -> None:
        if self.kind == "seq2seq":
            if self.src_len is None:
                raise ValueError("kind='seq2seq' requires src_len")
            if not 0 < self.src_len < self.image_size[0]:
                raise ValueError(
                    f"src_len {self.src_len} must be inside the "
                    f"{self.image_size[0]}-token stream"
                )

    @property
    def seq_len(self) -> int:
        assert self.kind in ("tokens", "seq2seq")
        return self.image_size[0]


DATASETS: Mapping[str, DatasetSpec] = {
    "mnist": DatasetSpec("mnist", (28, 28, 1), 10, 60_000, 10_000),
    "cifar10": DatasetSpec("cifar10", (32, 32, 3), 10, 50_000, 10_000),
    "imagenet": DatasetSpec("imagenet", (224, 224, 3), 1000, 1_281_167, 50_000),
    # "highres" is the reference's activation-memory stressor
    # (generate_synthetic_data.py:100-107): 512x512x3, 1000 classes.
    "highres": DatasetSpec("highres", (512, 512, 3), 1000, 50_000, 10_000),
    # Token workloads (new first-class axis, not reference parity): a standard
    # LM context and a long-context stressor for sequence parallelism.
    "synthtext": DatasetSpec("synthtext", (1024,), 32_768, 100_000, 10_000, kind="tokens"),
    "longctx": DatasetSpec("longctx", (8192,), 32_768, 20_000, 2_000, kind="tokens"),
    # 32k context: single-chip-trainable ONLY via the streaming flash
    # kernels (ops/flash_attention.py round 3) + fused head — XLA attention
    # would need a 2 GB score matrix per layer per 8k, and at 32k a single
    # layer's matrix alone exceeds one chip's HBM even under remat.
    "longctx32k": DatasetSpec("longctx32k", (32_768,), 32_768, 5_000, 500,
                              kind="tokens"),
    # Synthetic translation: the seq2seq workload (reference GNMT analog,
    # SURVEY.md §2 C13) as a prefix-LM stream — 128 source + 128 target tokens
    # (reference GNMT trains at max seq length 50-75 per side; see
    # models/seq2seq.py for the re-design rationale).
    "synthmt": DatasetSpec("synthmt", (256,), 32_768, 200_000, 20_000,
                           kind="seq2seq", src_len=128),
}

STRATEGIES = ("single", "dp", "gpipe", "pipedream", "sp", "tp", "fsdp", "ep")

# "auto" = Pallas flash-attention kernel on TPU, jnp elsewhere. Single source
# for the CLI choices, validate(), and models.transformer.set_attention_backend.
ATTENTION_BACKENDS = ("auto", "flash", "xla")

# Per-framework default batch sizes from the reference harness
# (run_template.sh:186-266,377-394; see BASELINE.md). For gpipe the tuple is
# (micro_batch_size, num_microbatches) and the effective global batch is the
# product (benchmark/mnist/mnist_gpipe.py:37-41). For pipedream the number is
# the global batch.
DEFAULT_BATCH: Mapping[str, Mapping[str, Any]] = {
    "single": {"mnist": 128, "cifar10": 64, "imagenet": 32, "highres": 32,
               "synthtext": 16, "longctx": 2, "longctx32k": 1, "synthmt": 64},
    "dp": {"mnist": 128, "cifar10": 64, "imagenet": 32, "highres": 32,
           "synthtext": 16, "longctx": 2, "longctx32k": 1, "synthmt": 64},
    "gpipe": {
        "mnist": (128, 24),
        "cifar10": (64, 32),
        "imagenet": (24, 12),
        "highres": (4, 12),
        "synthtext": (4, 8),
        "longctx": (1, 8),
        "longctx32k": (1, 4),
        "synthmt": (16, 8),
    },
    "pipedream": {"mnist": 512, "cifar10": 256, "imagenet": 128, "highres": 64,
                  "synthtext": 64, "longctx": 8, "longctx32k": 4, "synthmt": 128},
    "sp": {"mnist": 128, "cifar10": 64, "imagenet": 32, "highres": 32,
           "synthtext": 16, "longctx": 2, "longctx32k": 1, "synthmt": 32},
    # ep: per-device batch (batch and experts both shard the one mesh axis)
    "ep": {"synthtext": 8, "longctx": 1, "longctx32k": 1},
}


@dataclasses.dataclass(frozen=True)
class HardwareModel:
    """Bandwidth/memory constants feeding the partitioner cost model.

    Defaults describe one TPU v5e chip and its interconnect; the reference's
    equivalents (Ethernet 5 GB/s, PCIe 32 GB/s, 11/24 GB HBM) are inlined in
    bash at run_template.sh:414-420.
    """

    # Per-link ICI bandwidth (bytes/s). v5e: ~45 GB/s per direction per link.
    ici_bandwidth: float = 4.5e10
    # DCN (inter-host) bandwidth per host (bytes/s).
    dcn_bandwidth: float = 2.5e10
    # HBM per chip (bytes). v5e: 16 GiB.
    hbm_bytes: float = 16 * 1024**3
    # Peak bf16 matmul throughput per chip (FLOP/s). v5e: ~197 TFLOP/s.
    peak_flops: float = 1.97e14
    # Peak HBM bandwidth per chip (bytes/s). v5e: ~819 GB/s.
    hbm_bandwidth: float = 8.19e11

    def levels(self, num_hosts: int, chips_per_host: int):
        """Hierarchical (bandwidth, machines-per-group) levels, fastest first.

        The reference's hierarchical partitioner solves intra-node (PCIe) then
        inter-node (Ethernet) (optimizer_graph_hierarchical.py:282-297); on TPU
        the analogous levels are ICI within a pod slice and DCN across hosts.
        """
        levels = [(self.ici_bandwidth, chips_per_host)]
        if num_hosts > 1:
            levels.append((self.dcn_bandwidth, num_hosts))
        return levels


@dataclasses.dataclass(frozen=True)
class RunConfig:
    """One benchmark run = dataset x strategy x model x topology.

    CLI surface mirrors the reference's ``run.sh -b -f -g -n -m -q -p -s``
    (run/run/run.sh:16-47).
    """

    benchmark: str = "mnist"  # mnist | cifar10 | imagenet | highres
    strategy: str = "single"  # single | dp | gpipe | pipedream
    arch: str = "resnet18"
    num_devices: int = 1  # total chips (reference: gpus x nodes)
    num_hosts: int = 1
    synthetic: bool = True
    data_dir: Optional[str] = None
    # Asynchronous input pipeline (data/prefetch.py): the producer thread
    # runs batch production + shard_batch/device_put this many steps ahead
    # of the consuming loop through a bounded ring, overlapping host input
    # work and H2D transfers with device compute. 0 = synchronous
    # (--no-prefetch); batches are (epoch, step)-addressed, so losses are
    # bitwise identical either way.
    prefetch_depth: int = 2
    # Train-time augmentation for the on-disk (-s) image path, mirroring the
    # reference drivers' torchvision transforms (see data/ondisk.py).
    augment: bool = True

    # Training protocol (reference: EPOCHS=3, LOGINTER=25;
    # run_template.sh:71, run.sh:6).
    epochs: int = 3
    log_interval: int = 25
    batch_size: Optional[int] = None  # per-device for single/dp; global for pipedream
    micro_batch_size: Optional[int] = None  # gpipe/pipedream microbatch size
    num_microbatches: Optional[int] = None
    steps_per_epoch: Optional[int] = None  # override dataset-size-derived count

    # Optimizer (reference defaults: mnist/cifar lr .01 momentum .5;
    # imagenet .1/.9 + wd 1e-4, step decay /10 every 30 epochs —
    # mnist_pytorch.py:153-156, imagenet_pytorch.py:44-50,225-229).
    # None = per-workload default: "adam" for seq2seq benchmarks (the
    # reference translation runtime trains with AdamWithWeightStashing,
    # runtime/adam.py + translation/main_with_runtime.py:251-256), else "sgd".
    optimizer: Optional[str] = None  # sgd | adam
    adam_beta1: float = 0.9  # reference betas=(0.9, 0.999)
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    lr: Optional[float] = None
    momentum: Optional[float] = None
    weight_decay: Optional[float] = None
    lr_step_epochs: int = 30
    lr_step_gamma: float = 0.1
    # Goyal-et-al gradual warmup (imagenet_horovod.py:258-275): ramp lr from
    # base to base*world over this many leading epochs, per-batch
    # granularity. 0 disables (the reference enables it only in the Horovod
    # ImageNet driver, warmup_epochs=5).
    warmup_epochs: int = 0
    scale_lr_by_world: bool = True  # Horovod parity: lr x world (mnist_horovod.py:226)
    # ZeRO-1 for dp: shard the optimizer state (momentum, adam m/v) over the
    # 'data' axis while params stay replicated — placement-only, XLA shards
    # the update and all-gathers the delta. No reference analog (its DP
    # replicates everything).
    shard_opt_state: bool = False
    # Explicit sharded weight update for dp (ZeRO-1 via shard_map, not
    # GSPMD placement): gradients reduce-scatter over 'data', the packed
    # flat-vector optimizer state and the weight update live 1/world per
    # chip (contiguous slice), updated params all-gather back. Same wire
    # bytes as the replicated ring allreduce (RS + AG = 2(r-1)/r x P), but
    # optimizer memory and update FLOPs drop ~world x. See
    # parallel/dp.py DPShardedEngine.
    dp_shard_update: bool = False
    # Wire dtype for dp's explicit gradient collectives (EQuARX-style
    # compressed allreduce): "float32" (exact; the default), "bfloat16"
    # (halves gradient wire bytes), or "int8" (quarter wire bytes:
    # per-bucket absmax scaling + stochastic rounding on the gradient
    # partials, deterministic under the run seed; accuracy parity gated by
    # the digits matrix — tools/accparity.py dp-bf16/dp-int8 engines).
    # Values "f32"/"bf16" normalize. Any non-f32 setting routes dp through
    # the explicit shard_map collective engine even without dp_shard_update.
    allreduce_dtype: str = "float32"
    # Comm/compute overlap for the explicit dp engine: split the packed
    # flat gradient into this many contiguous, layer-aligned buckets, each
    # riding its own reduce-scatter as the backward unwinds, and (with
    # --dp-shard-update) keep the parameters SHARDED between steps so the
    # forward all-gathers each bucket just-in-time before the first layer
    # that consumes it — earlier layers' compute hides later buckets' wire
    # time under XLA's latency-hiding scheduler (distributed.comm_flags()).
    # 1 (the default) compiles the exact monolithic-collective program.
    comm_buckets: int = 1
    # Gradient accumulation: K micro-steps between optimizer updates, grads
    # averaged (Horovod backward_passes_per_step / batches_per_allreduce
    # parity, imagenet_horovod.py:131-139; dp with SGD also scales lr by K —
    # the linear-scaling heuristic is gated to SGD in train/loop.py). The
    # per-step batch becomes K x the configured batch. single/dp/tp/fsdp.
    grad_accum_steps: int = 1

    # Pipeline topology.
    num_stages: Optional[int] = None  # defaults to num_devices // dp_replicas
    dp_replicas: int = 1  # hybrid PPxDP: replicas per stage (uniform)
    # Uneven hybrid PPxDP: per-stage replication factors, e.g. (1, 3) — the
    # reference optimizer's heterogeneous plans (run_template.sh:436-498).
    # Executed by parallel/hetero.py over a flat 'pipe' mesh axis; mutually
    # exclusive with dp_replicas > 1. Uniform tuples route to the regular
    # 2-D-mesh strategies.
    stage_replication: Optional[Tuple[int, ...]] = None
    # Interleaved schedule (gpipe only): each device owns this many model
    # chunks, cutting the synchronous-pipeline bubble by the same factor at
    # the cost of more (cheap, ICI-neighbor) rotations. Requires
    # num_microbatches % stages == 0 when > 1.
    virtual_stages: int = 1
    # Pipeline schedule for the gpipe-family strategies — a TIMETABLE the
    # schedule-programmable runtime executes (partition/schedule.py data,
    # parallel/pipeline_rt.py engine), not a separate engine per schedule:
    # * "fill-drain"  — GPipe flush (the autodiff scan; the default, and
    #                   bitwise the legacy gpipe program),
    # * "1f1b"        — synchronous 1F1B (same weights every microbatch,
    #                   one update per step; bubble 2(S-1)/(3M+2(S-1))),
    # * "interleaved" — interleaved 1F1B over S x virtual_stages chunks,
    # * "zero-bubble" — ZB-H1-style split backward: weight-grad events
    #                   fill the drain bubble ((S-1)/(3M+S-1)); composes
    #                   with virtual_stages > 1,
    # * "zero-bubble-h2" — ZB-H2-style: zb_h2_stash extra in-flight
    #                   microbatches per chunk and the trailing W events
    #                   deferred past the step boundary (steady-state
    #                   bubble -> 0 at the price of the extra stash),
    # * "searched"    — partition/schedule_search.py: deterministic
    #                   budgeted local search seeded by both heuristics;
    #                   never packs worse than 1f1b/zero-bubble, keeps
    #                   their 1F1B activation memory.
    # pipedream keeps its own ASYNC 1F1B engine (weight stashing).
    pipe_schedule: str = "fill-drain"
    # zero-bubble-h2's extra in-flight activation stash, microbatches per
    # chunk. More stash hides more warmup idle (steady bubble ~
    # max(0, S-1-stash)/(3M+S-1-stash)) but costs that many extra stashed
    # boundary activations per chunk in the planner's memory term.
    zb_h2_stash: int = 1
    # The searched packer's move-evaluation budget and rng seed
    # (partition/schedule_search.py). Same (budget, seed) -> bitwise the
    # same table; the planner prices searched candidates at exactly these
    # values so the priced table is the one the runtime executes.
    sched_search_budget: int = 256
    sched_search_seed: int = 0
    # Cost model for the pipeline timetable (partition/schedule.py):
    # * "unit"    — the F=B=W unit-cost grids (the PR 7 tables, bitwise);
    # * "profile" — per-chunk F/B/W cost vectors summed from the
    #   --auto-partition profile graph over the chosen stage bounds
    #   (quantize_cost_vectors), so uneven stage splits execute on
    #   timetables packed for their true costs. Event schedules only
    #   (the fill-drain autodiff scan is lockstep by construction).
    pipe_costs: str = "unit"
    # Resolved per-chunk (f, b, w) half-tick cost vectors — normally
    # written by the auto-partition path (or restored from a persisted
    # plan), but settable directly for tests/tools.
    pipe_cost_vectors: Optional[Tuple[Tuple[int, ...], Tuple[int, ...],
                                      Tuple[int, ...]]] = None
    # A prior run's --trace JSON: --auto-partition's schedule advisor
    # folds the MEASURED bubble fraction reduced from it
    # (telemetry/bubble.py) into its ranking, outranking the analytic
    # value for the schedule the trace recorded (ROADMAP item 2c).
    schedule_trace: Optional[str] = None
    # Composed tensor x pipeline parallelism (gpipe + transformer archs):
    # each pipeline stage's blocks are Megatron-sliced this many ways over a
    # 'model' mesh axis inside the stage (parallel/tpp.py). num_devices =
    # tp_size x stages. No reference analog (its engines compose PP with DP
    # only); the TPU-native composition rides intra-stage ICI neighbors.
    tp_size: int = 1
    # PipeDream macrobatch mode (runtime/optimizer.py:36-52,119-164):
    # accumulate gradients across update_interval microbatches inside the
    # 1F1B schedule and step once per interval (grads averaged /K). The
    # reference caps weight stashing at 2 versions here and accepts version
    # staleness; our stash ring keeps exact per-microbatch forward weights
    # (documented deviation in parallel/pipedream.py).
    update_interval: int = 1

    # Auto-parallelism: profile the model and choose stage bounds with the
    # hierarchical partitioner before building the pipeline strategies
    # (reference: the whole PipeDream phase 1-3 pipeline).
    auto_partition: bool = False
    profile_mode: str = "flops"  # "flops" (device-free) | "time" (measured)
    # `--plan auto` (partition/planner.py): solve the FULL dp/pp/tp mix +
    # stage split + schedule from the profile under the per-chip HBM cap,
    # then rewrite this config onto the winning engines (dp ZeRO-1,
    # gpipe/pipeline_rt with --dp-shard-update, tp) before anything runs.
    # Resolved at run start (train/loop.py / parallel/api.py) via
    # planner.resolve_auto_plan; the pre-plan config must leave every
    # mix-shaping flag at its default — the planner owns them. "manual"
    # (default) = the flags mean what they say.
    plan: str = "manual"
    # Explicit per-chunk stage bounds over the model's layer chain for the
    # pipeline strategies (len = stages * virtual_stages + 1, starting at
    # 0) — how a solved plan's split reaches the engine, and settable
    # directly (--plan-bounds) so an explicitly-flagged run can execute
    # the exact same split a --plan auto run chose (the bitwise pin).
    plan_bounds: Optional[Tuple[int, ...]] = None

    # MoE (transformer_moe_* archs): Switch router load-balance loss weight
    # and static per-expert capacity = ceil(cf * tokens / experts).
    moe_aux_weight: float = 0.01
    moe_capacity_factor: float = 1.25

    # Label smoothing for the training objective (GNMT parity: the reference
    # translation workload trains with smoothing 0.1,
    # runtime/translation seq2seq label-smoothing module). None = per-workload
    # default (0.1 for seq2seq benchmarks, 0 otherwise).
    label_smoothing: Optional[float] = None

    # Numerics.
    compute_dtype: str = "bfloat16"  # MXU-native; tests use float32
    # "auto" = Pallas flash-attention kernel on TPU, jnp elsewhere.
    attention_backend: str = "auto"  # auto | flash | xla
    # Fused LM-head projection+cross-entropy on the training path
    # (ops/fused_xent.py): the [tokens, vocab] logits never hit HBM. Applies
    # to models whose head supports it (the token/seq2seq workloads).
    fused_head_loss: bool = True
    param_dtype: str = "float32"
    # jax.checkpoint each (microbatch, stage) in pipeline modes — parity with
    # torchgpipe's default activation checkpointing.
    remat_stages: bool = True
    # jax.checkpoint each LAYER in the one-apply strategies (single/dp/tp/
    # fsdp): the backward recomputes layers instead of saving interiors,
    # capping live activations at one layer's working set. Off by default
    # (XLA's fusion usually wins); required for XLA-attention long-context
    # training on one chip, where each layer otherwise keeps a [B, H, T, T]
    # score matrix alive into the backward. Incompatible with MoE archs: the
    # router aux losses are collected through a trace-time side channel
    # (models/moe.py collect_aux_losses) that cannot escape a checkpointed
    # trace.
    remat_layers: bool = False
    seed: int = 1  # reference seeds torch.manual_seed(1) (imagenet_pytorch.py:58-66)

    # Checkpoint/resume (reference: per-stage checkpoint.{stage}.pth.tar per
    # epoch, main_with_runtime.py:580-584; resume :241-262). Saves go through
    # the atomic commit protocol in train/checkpoint.py (tmp -> fsync ->
    # COMMIT marker -> rename); resume picks the newest checkpoint that
    # VERIFIES against its manifest, falling back past torn or corrupt ones.
    checkpoint_dir: Optional[str] = None
    resume: bool = False
    # Step-granular checkpoints: also commit a mid-epoch checkpoint every K
    # completed steps (epoch_N_step_S), carrying the full resume state
    # (global step, interior data-iterator position, metric-logger counters,
    # seed) so a kill mid-epoch resumes bit-for-bit. None = per-epoch only.
    checkpoint_every_steps: Optional[int] = None
    # Retention: keep only the newest N committed checkpoints (older ones
    # and stale .tmp dirs are GC'd after each commit). None = keep all.
    keep_checkpoints: Optional[int] = None
    # Elastic world-size resume (train/reshard.py): when the checkpoint's
    # recorded world shape mismatches the current mesh, reshard the ZeRO-1
    # flat state between world sizes (a pure permutation — f32 bitwise)
    # instead of raising CheckpointShapeError. The lr world-scaling factor
    # stays pinned to the LAUNCH world recorded in the checkpoint, and the
    # global batch must be preserved across the reshape for the
    # (epoch, step)-addressed data streams to line up.
    elastic_resume: bool = False
    # World-invariant reduction order for the dp ZeRO-1 engine: compute
    # gradients in E fixed slices of the GLOBAL batch and reduce them over
    # a canonical balanced binary tree (local fold over each device's
    # contiguous slices + butterfly allreduce across devices) instead of
    # local-sum + psum_scatter. The reduction tree is then a function of E
    # alone, so an elastic run checkpointed at world N and resumed at
    # world M (both dividing E, powers of two) replays the SAME f32 bits —
    # the numerical contract behind chaosbench's shrink/grow
    # trajectory_match. Costs log2(world) full-vector exchange rounds vs
    # the ring reduce-scatter's (world-1)/world. None = off (the default
    # wire path, bitwise-pinned vs GSPMD at a fixed world).
    elastic_slices: Optional[int] = None
    # Deterministic fault injection (ddlbench_tpu/faults/): repeatable
    # KIND@EPOCH:STEP specs, e.g. ("kill@2:5", "nan-loss@1:3"). Empty =
    # disarmed; the hooks then cost one falsy check each.
    inject: Tuple[str, ...] = ()

    # Failure detection (reference has none beyond a 120-min process-group
    # timeout, SURVEY.md §5.3): abort/warn/ignore on non-finite loss, and an
    # optional per-sync hang deadline that stack-dumps and kills the process.
    # DEPRECATED flag surface: superseded by anomaly_policy below (kept as a
    # working alias — resolved_anomaly_policy() falls back to it).
    nan_policy: str = "abort"  # abort | warn | ignore
    hang_timeout_s: Optional[float] = None

    # Stability guard (ddlbench_tpu/guard/). Setting anomaly_policy (or
    # loss_scale) ARMS on-device anomaly detection in the guarded engines
    # (single, dp incl. the explicit shard_map engine, gpipe, tpp,
    # pipedream): each train step folds a fused (loss_finite & grad_finite,
    # global_grad_norm) pair into its metrics, synced on the existing
    # interval path. Policies beyond the legacy abort/warn/ignore:
    # * "skip"   — drop an anomalous update IN-STEP (lax select): params and
    #              optimizer state stay bitwise untouched, including ZeRO-1
    #              sharded slices.
    # * "rewind" — restore the last committed checkpoint via the
    #              latest_valid resume path and replay (the (epoch, step)-
    #              addressed data stream fast-forwards deterministically);
    #              requires checkpoint_dir.
    # None leaves the guard disarmed: engines compile their pre-guard
    # programs and non-finite losses follow nan_policy as before.
    anomaly_policy: Optional[str] = None
    # Consecutive anomalies (skipped steps, backoffs, spikes — or rewinds
    # for the same step) tolerated before escalating to TrainingFailure.
    anomaly_budget: int = 3
    # Loss scaling for the bf16 compute/wire paths: "dynamic" (growth x2
    # after a clean streak, backoff x1/2 on overflow, overflowed updates
    # dropped in-step) or a fixed positive float. Power-of-two dynamic
    # scales keep f32 runs bitwise identical to unscaled ones. None = off.
    loss_scale: Optional[Any] = None
    # Host-side EWMA spike detector: a window whose mean grad norm exceeds
    # factor x EWMA is an anomaly (the diverged-but-finite case).
    grad_spike_factor: float = 10.0

    # Step-level telemetry (ddlbench_tpu/telemetry/): host-side span tracing
    # into a bounded ring buffer, exported as a Chrome-trace-event JSON
    # (Perfetto-loadable) at `trace`. None disables tracing entirely — the
    # hot loop then pays one no-op check per span site and nothing else.
    trace: Optional[str] = None
    trace_capacity: int = 200_000  # ring-buffer bound (events)
    # Whole-run device/XLA profile directory (jax.profiler.trace), and an
    # optional [start, stop) global-step window for the capture — a short
    # window keeps the profile small enough to open while the host trace
    # above covers the whole run. Steps are counted over the whole run
    # (epoch boundaries do not reset the counter; warmup is excluded).
    trace_dir: Optional[str] = None
    xla_trace_steps: Optional[Tuple[int, int]] = None
    # Compiled-program audit manifest (telemetry/audit.py): AOT-lower the
    # train step once before the run, extract flops / HBM components / the
    # per-collective ledger out of the optimized HLO, cross-check the
    # comm_stats wire-byte formulas, and write the ledger JSON here. One
    # extra trace of the already-compiled program shapes; never executes.
    audit: Optional[str] = None

    # Activation/gradient deep-dive logging (torchlogger analog, SURVEY.md
    # §5.5; reference profiler main.py:543-582): every activation_log_freq
    # epochs, dump per-layer activations + dLoss/d(activation) for the first
    # activation_log_steps minibatches as npz files under activation_log_dir.
    activation_log_dir: Optional[str] = None
    activation_log_freq: int = 1
    activation_log_steps: int = 1

    hardware: HardwareModel = dataclasses.field(default_factory=HardwareModel)

    # ---- derived ----

    def dataset(self) -> DatasetSpec:
        return DATASETS[self.benchmark]

    def resolved_optimizer(self) -> str:
        if self.optimizer is not None:
            return self.optimizer
        return "adam" if self.dataset().kind == "seq2seq" else "sgd"

    def resolved_lr(self) -> float:
        if self.lr is not None:
            return self.lr
        if self.resolved_optimizer() == "adam":
            return 1e-3  # typical Adam scale (reference passes lr via flag)
        if self.dataset().kind in ("tokens", "seq2seq"):
            return 0.01
        return 0.1 if self.benchmark in ("imagenet", "highres") else 0.01

    def resolved_allreduce_dtype(self) -> str:
        """Canonical allreduce_dtype: 'float32', 'bfloat16', or 'int8'."""
        alias = {"f32": "float32", "float32": "float32",
                 "bf16": "bfloat16", "bfloat16": "bfloat16",
                 "int8": "int8"}
        try:
            return alias[self.allreduce_dtype]
        except KeyError:
            raise ValueError(
                f"unknown allreduce_dtype {self.allreduce_dtype!r} "
                f"(choose f32/float32, bf16/bfloat16, or int8)")

    def dp_overlap_engine(self) -> bool:
        """True when dp runs the OVERLAPPED sharded-update engine: params
        stay sharded between steps (just-in-time bucketed all-gather in the
        forward) and the backward reduce-scatters per bucket. Requires both
        the sharded update and more than one comm bucket; with one bucket
        the engine compiles the exact monolithic (PR 3) program."""
        return (self.dp_explicit_collectives() and self.dp_shard_update
                and self.comm_buckets > 1)

    def dp_explicit_collectives(self) -> bool:
        """True when dp runs the explicit shard_map collective engine
        (sharded weight update, compressed gradient collectives, and/or
        bucketed collectives) instead of leaving the gradient allreduce to
        GSPMD. comm_buckets > 1 routes here like a non-f32 wire dtype
        does: an f32 bucketed run is the replicated engine with one psum
        per bucket (bitwise vs GSPMD dp for non-BN models)."""
        return self.strategy == "dp" and (
            self.dp_shard_update
            or self.comm_buckets > 1
            or self.resolved_allreduce_dtype() != "float32")

    def pipe_shard_engine(self) -> bool:
        """True when the gpipe-family pipeline runtime composes with the
        ZeRO-1 shard axis (hybrid PP x ZeRO-1, ISSUE 8): each stage's
        packed parameter row and optimizer state stay flat and SHARDED
        across the pipe mesh's 'data' axis between steps, the forward
        all-gathers each bucket just-in-time, and the post-scan gradient
        pmean becomes a bucketed reduce-scatter feeding one sharded
        update per step. Selected by --dp-shard-update on -f gpipe
        (same flag as dp's ZeRO-1 engine; validate() scopes it to the
        2-D data x stage mesh — no tp, no hetero replication)."""
        return self.strategy == "gpipe" and self.dp_shard_update

    def resolved_label_smoothing(self) -> float:
        if self.label_smoothing is not None:
            return self.label_smoothing
        return 0.1 if self.dataset().kind == "seq2seq" else 0.0

    def resolved_anomaly_policy(self) -> str:
        """The ONE anomaly-policy surface: anomaly_policy when set, else the
        legacy nan_policy alias (whose values are a subset)."""
        return (self.anomaly_policy if self.anomaly_policy is not None
                else self.nan_policy)

    def resolved_loss_scale(self):
        """None (off), "dynamic", or a fixed positive float."""
        if self.loss_scale is None:
            return None
        if isinstance(self.loss_scale, str):
            if self.loss_scale == "dynamic":
                return "dynamic"
            try:
                v = float(self.loss_scale)
            except ValueError:
                raise ValueError(
                    f"loss_scale must be 'dynamic' or a positive float; "
                    f"got {self.loss_scale!r}")
        else:
            v = float(self.loss_scale)
        import math

        if not math.isfinite(v) or v <= 0:
            raise ValueError(
                f"loss_scale must be 'dynamic' or a positive float; "
                f"got {self.loss_scale!r}")
        return v

    def guard_armed(self) -> bool:
        """True when the engines should compile on-device anomaly
        detection (and loss scaling) into their train steps."""
        return self.anomaly_policy is not None or self.loss_scale is not None

    def resolved_momentum(self) -> float:
        if self.momentum is not None:
            return self.momentum
        return 0.9 if self.benchmark in ("imagenet", "highres") else 0.5

    def resolved_weight_decay(self) -> float:
        if self.weight_decay is not None:
            return self.weight_decay
        return 1e-4 if self.benchmark in ("imagenet", "highres") else 0.0

    def resolved_stages(self) -> int:
        if self.stage_replication:
            return len(self.stage_replication)
        if self.num_stages is not None:
            return self.num_stages
        return max(1, self.num_devices
                   // (max(1, self.dp_replicas) * max(1, self.tp_size)))

    def resolved_batches(self) -> Tuple[int, int]:
        """Return (micro_batch_size, num_microbatches).

        For single/dp, num_microbatches == 1 and micro_batch_size is the
        per-device batch. Defaults follow the reference matrix (BASELINE.md).
        """
        if self.strategy in ("single", "dp", "sp", "tp", "fsdp", "ep"):
            key = self.strategy if self.strategy in DEFAULT_BATCH else "dp"
            b = self.batch_size or DEFAULT_BATCH[key][self.benchmark]
            return int(b), 1
        if self.strategy == "gpipe":
            if self.micro_batch_size and self.num_microbatches:
                # fully explicit grammar: the default matrix is not
                # consulted (benchmarks outside it work with both flags)
                return int(self.micro_batch_size), int(self.num_microbatches)
            mb, chunks = DEFAULT_BATCH["gpipe"][self.benchmark]
            mb = self.micro_batch_size or mb
            if self.num_microbatches:
                chunks = self.num_microbatches
            elif self.batch_size:
                # interpret batch_size as the effective global batch
                chunks = max(1, self.batch_size // mb)
            return int(mb), int(chunks)
        # pipedream: global batch split into microbatches of micro_batch_size.
        global_b = self.batch_size or DEFAULT_BATCH["pipedream"][self.benchmark]
        mb = self.micro_batch_size or max(1, global_b // (2 * self.resolved_stages()))
        chunks = self.num_microbatches or max(1, global_b // mb)
        return int(mb), int(chunks)

    def global_batch(self) -> int:
        mb, chunks = self.resolved_batches()
        accum = self.grad_accum_steps if self.strategy in (
            "single", "dp", "tp", "fsdp") else 1
        if self.strategy in ("single", "sp", "tp"):
            return mb * accum  # sp/tp shard sequence/features, not the batch
        if self.strategy in ("dp", "fsdp", "ep"):
            return mb * self.num_devices * accum
        if self.stage_replication:
            # hetero pipeline: replicas split each microbatch's rows, so the
            # global batch carries no replication factor
            return mb * chunks
        return mb * chunks * max(1, self.dp_replicas)

    def validate(self) -> None:
        if self.benchmark not in DATASETS:
            raise ValueError(f"unknown benchmark {self.benchmark!r}")
        if self.strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {self.strategy!r}")
        if self.strategy == "single" and self.num_devices != 1:
            raise ValueError("single strategy uses exactly 1 device")
        from ddlbench_tpu.train.watchdog import NAN_POLICIES

        if self.nan_policy not in NAN_POLICIES:
            raise ValueError(f"unknown nan_policy {self.nan_policy!r}")
        if self.anomaly_policy is not None:
            from ddlbench_tpu.guard.policy import ANOMALY_POLICIES

            if self.anomaly_policy not in ANOMALY_POLICIES:
                raise ValueError(
                    f"unknown anomaly_policy {self.anomaly_policy!r} "
                    f"(choose from {', '.join(ANOMALY_POLICIES)})")
            if self.anomaly_policy == "rewind" and self.checkpoint_dir is None:
                raise ValueError(
                    "anomaly_policy='rewind' needs --checkpoint-dir (the "
                    "rewind target is the last committed checkpoint)")
            from ddlbench_tpu.guard.policy import GUARD_UNWIRED_STRATEGIES

            if self.anomaly_policy == "skip" and \
                    self.strategy in GUARD_UNWIRED_STRATEGIES:
                raise ValueError(
                    f"anomaly_policy='skip' (in-step update drop) needs "
                    f"device-guard wiring, which the {self.strategy!r} "
                    f"engine lacks; use abort/warn/rewind there")
        if self.anomaly_budget < 1:
            raise ValueError("anomaly_budget must be >= 1")
        self.resolved_loss_scale()  # raises on malformed values
        if self.loss_scale is not None and self.strategy == "pipedream":
            raise ValueError(
                "loss_scale is wired into the one-update-per-step train "
                "steps (single/dp/gpipe incl. tp_size > 1, sp/tp/fsdp/ep); "
                "pipedream's per-microbatch updates would need per-event "
                "unscaling and run unscaled")
        if self.grad_spike_factor <= 1.0:
            raise ValueError("grad_spike_factor must be > 1")
        if self.attention_backend not in ATTENTION_BACKENDS:
            raise ValueError(
                f"unknown attention_backend {self.attention_backend!r}"
            )
        if self.hang_timeout_s is not None and self.hang_timeout_s <= 0:
            raise ValueError("hang_timeout_s must be positive")
        if self.checkpoint_every_steps is not None:
            if self.checkpoint_every_steps < 1:
                raise ValueError("checkpoint_every_steps must be >= 1")
            if self.checkpoint_dir is None:
                raise ValueError(
                    "checkpoint_every_steps needs --checkpoint-dir for the "
                    "checkpoint location")
        if self.keep_checkpoints is not None and self.keep_checkpoints < 1:
            raise ValueError(
                "keep_checkpoints must be >= 1 (the newest checkpoint is "
                "never dropped)")
        if self.elastic_resume and self.checkpoint_dir is None:
            raise ValueError(
                "elastic_resume resharding needs --checkpoint-dir (there "
                "is no checkpoint to reshard without one)")
        if self.elastic_slices is not None:
            E = self.elastic_slices
            if E < 1 or (E & (E - 1)):
                raise ValueError(
                    f"elastic_slices must be a positive power of two (the "
                    f"canonical balanced reduction tree over E leaves must "
                    f"decompose at any world cut); got {E}")
            if self.strategy != "dp" or not self.dp_shard_update:
                raise ValueError(
                    "elastic_slices (world-invariant reduction order) runs "
                    "on the dp ZeRO-1 engine (-f dp --dp-shard-update)")
            w = self.num_devices
            if w & (w - 1) or E % w:
                raise ValueError(
                    f"elastic_slices ({E}) needs a power-of-two device "
                    f"count dividing it (got {w}): device boundaries must "
                    f"align with subtrees of the canonical reduction tree")
            if self.global_batch() % E:
                raise ValueError(
                    f"global batch ({self.global_batch()}) must divide "
                    f"into elastic_slices ({E}) equal slices")
            if self.grad_accum_steps > 1:
                raise ValueError(
                    "elastic_slices already slices the global batch; "
                    "grad_accum_steps > 1 is not composed with it")
            if self.resolved_allreduce_dtype() != "float32":
                raise ValueError(
                    "elastic_slices is the exact-replay mode: quantized "
                    "wire dtypes fold device indices into their rounding "
                    "streams and can never be world-invariant (use f32)")
        if self.inject:
            from ddlbench_tpu.faults import parse_injections

            parse_injections(self.inject)  # raises on bad grammar/kind
        if self.prefetch_depth < 0:
            raise ValueError("prefetch_depth must be >= 0 (0 = synchronous)")
        if self.trace_capacity < 1:
            raise ValueError("trace_capacity must be >= 1")
        if self.xla_trace_steps is not None:
            a, b = self.xla_trace_steps
            if a < 0 or b <= a:
                raise ValueError(
                    f"xla_trace_steps must be a [start, stop) window with "
                    f"0 <= start < stop; got {self.xla_trace_steps}")
            if self.trace_dir is None:
                raise ValueError(
                    "xla_trace_steps needs --trace-dir for the profile "
                    "output location")
        if self.label_smoothing is not None and not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError("label_smoothing must be in [0, 1)")
        if self.strategy == "sp" and self.dataset().kind not in ("tokens", "seq2seq"):
            raise ValueError(
                "sp (sequence parallelism) requires a token or seq2seq benchmark")
        if self.strategy == "ep":
            if self.dataset().kind != "tokens":
                raise ValueError("ep (expert parallelism) requires a token benchmark")
            if "moe" not in self.arch:
                raise ValueError("ep (expert parallelism) requires an MoE arch")
        if self.remat_layers and "moe" in self.arch:
            raise ValueError(
                "remat_layers is incompatible with MoE archs (router aux "
                "losses cannot escape a checkpointed trace); use "
                "remat_stages via a pipeline strategy instead")
        if self.remat_layers and self.strategy not in ("single", "dp", "tp",
                                                       "fsdp"):
            raise ValueError(
                f"remat_layers applies to the one-apply strategies "
                f"(single/dp/tp/fsdp), not {self.strategy!r} — the pipeline "
                f"strategies checkpoint per (microbatch, stage) via "
                f"remat_stages, and sp/ep bound activation memory by "
                f"sharding the sequence/experts instead")
        if self.stage_replication is not None:
            repl = tuple(self.stage_replication)
            if self.strategy not in ("gpipe", "pipedream"):
                raise ValueError(
                    "stage_replication applies to the pipeline strategies")
            if not repl or any(r < 1 for r in repl):
                raise ValueError("stage_replication factors must be >= 1")
            if self.dp_replicas > 1:
                raise ValueError(
                    "stage_replication and dp_replicas are mutually "
                    "exclusive (the tuple already encodes replication)")
            if sum(repl) != self.num_devices:
                raise ValueError(
                    f"stage_replication {repl} sums to {sum(repl)}; "
                    f"num_devices is {self.num_devices}")
            if self.num_stages is not None and self.num_stages != len(repl):
                raise ValueError(
                    f"num_stages ({self.num_stages}) != "
                    f"len(stage_replication) ({len(repl)})")
            mb, _ = self.resolved_batches()
            bad = [s for s, r in enumerate(repl) if mb % r]
            if bad:
                raise ValueError(
                    f"micro-batch {mb} must be divisible by every "
                    f"replication factor; stages {bad} of {repl} are not")
            if self.virtual_stages > 1:
                raise ValueError(
                    "stage_replication and virtual_stages (interleaved "
                    "schedule) are mutually exclusive")
        elif self.strategy in ("gpipe", "pipedream"):
            s = self.resolved_stages()
            if s * max(1, self.dp_replicas) * max(1, self.tp_size) \
                    != self.num_devices:
                raise ValueError(
                    f"stages ({s}) x dp_replicas ({self.dp_replicas}) x "
                    f"tp_size ({self.tp_size}) must equal "
                    f"num_devices ({self.num_devices})"
                )
        if self.tp_size < 1:
            raise ValueError("tp_size must be >= 1")
        if self.tp_size > 1:
            if self.strategy != "gpipe":
                raise ValueError(
                    "tp_size > 1 (composed tensor x pipeline parallelism) "
                    "runs on the gpipe strategy (parallel/tpp.py)")
            if self.dataset().kind not in ("tokens", "seq2seq"):
                raise ValueError(
                    "tp_size > 1 requires a token or seq2seq benchmark "
                    "(transformer blocks are what gets Megatron-sliced)")
            if self.stage_replication is not None:
                raise ValueError(
                    "tp_size > 1 composes with uniform pipeline stages "
                    "(plus dp_replicas for 3-D parallelism); "
                    "stage_replication must stay default")
            if self.virtual_stages > 1:
                raise ValueError(
                    "tp_size > 1 with the interleaved schedule is not "
                    "supported")
        if self.virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        from ddlbench_tpu.partition.schedule import PIPE_SCHEDULES

        if self.pipe_schedule not in PIPE_SCHEDULES:
            raise ValueError(
                f"unknown pipe_schedule {self.pipe_schedule!r} "
                f"(choose from {', '.join(PIPE_SCHEDULES)})")
        if self.pipe_schedule != "fill-drain":
            if self.strategy != "gpipe":
                raise ValueError(
                    f"pipe_schedule={self.pipe_schedule!r} runs on the "
                    f"gpipe strategy's schedule runtime "
                    f"(parallel/pipeline_rt.py); pipedream is the ASYNC "
                    f"1F1B engine and {self.strategy!r} has no pipeline")
            if self.tp_size > 1:
                raise ValueError(
                    "tp_size > 1 composes with the fill-drain schedule "
                    "(parallel/tpp.py); event-mode schedules are scoped "
                    "to the 2-D data x stage mesh")
            if self.stage_replication is not None:
                raise ValueError(
                    "stage_replication (hetero pipeline) executes the "
                    "fill-drain schedule only")
            # 1f1b/zero-bubble at virtual_stages > 1 are the COMPOSED
            # schedules (the interleaved / W-deferring interleaved tables)
            # since PR 18 — no V gate here; the M % S grammar below holds
            # for the whole event family.
        if self.zb_h2_stash < 0:
            raise ValueError("zb_h2_stash must be >= 0")
        if self.sched_search_budget < 0:
            raise ValueError("sched_search_budget must be >= 0")
        if self.update_interval < 1:
            raise ValueError("update_interval must be >= 1")
        if self.update_interval > 1:
            # uniform stage_replication tuples normalize to dp_replicas in
            # make_strategy and ARE macrobatch-compatible; only genuinely
            # uneven plans conflict
            uneven = (self.stage_replication
                      and len(set(self.stage_replication)) > 1)
            if self.strategy != "pipedream" or uneven:
                raise ValueError(
                    "update_interval > 1 (PipeDream macrobatch) requires the "
                    "uniform pipedream strategy")
            _, chunks = self.resolved_batches()
            if chunks % self.update_interval:
                raise ValueError(
                    f"num_microbatches ({chunks}) must be divisible by "
                    f"update_interval ({self.update_interval})")
        if self.grad_accum_steps < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        if self.optimizer is not None and self.optimizer not in ("sgd", "adam"):
            raise ValueError(f"unknown optimizer {self.optimizer!r}")
        if self.grad_accum_steps > 1 and self.strategy not in (
                "single", "dp", "tp", "fsdp"):
            raise ValueError(
                "grad_accum_steps > 1 is supported on single/dp/tp/fsdp "
                "(pipeline strategies already micro-batch)")
        if self.shard_opt_state and self.strategy != "dp":
            raise ValueError(
                "shard_opt_state (ZeRO-1) applies to the dp strategy "
                "(fsdp already shards everything)")
        self.resolved_allreduce_dtype()  # raises on unknown values
        if self.comm_buckets < 1:
            raise ValueError("comm_buckets must be >= 1")
        if self.comm_buckets > 1 and self.strategy != "dp" and \
                not self.pipe_shard_engine():
            raise ValueError(
                "comm_buckets > 1 (bucketed gradient collectives) applies "
                "to the dp strategy's explicit collective engine (-f dp; "
                "combine with --dp-shard-update for the fully overlapped "
                "just-in-time all-gather) or to -f gpipe with "
                "--dp-shard-update (hybrid PP x ZeRO-1 bucket count)")
        if self.dp_shard_update and self.strategy not in ("dp", "gpipe"):
            raise ValueError(
                "dp_shard_update (sharded weight update) applies to the dp "
                "strategy or to -f gpipe (hybrid PP x ZeRO-1 over the pipe "
                "mesh's 'data' axis; fsdp already shards everything)")
        if self.pipe_shard_engine():
            if self.tp_size > 1:
                raise ValueError(
                    "dp_shard_update on gpipe (hybrid PP x ZeRO-1) is "
                    "scoped to the 2-D data x stage mesh; tp_size > 1 "
                    "keeps the replicated update")
            if self.stage_replication is not None:
                raise ValueError(
                    "dp_shard_update on gpipe needs the uniform 2-D mesh; "
                    "stage_replication (hetero pipeline) keeps the "
                    "replicated update")
        if self.plan not in ("manual", "auto"):
            raise ValueError(
                f"unknown plan mode {self.plan!r} (choose manual or auto)")
        if self.plan == "auto":
            if self.strategy != "gpipe":
                raise ValueError(
                    "--plan auto solves the dp/pp/tp mix from the gpipe "
                    "batch grammar (micro-batch x microbatches = the "
                    "global batch the plan preserves); pass -f gpipe — "
                    "the winner may rewrite the strategy to dp/tp/single")
            if self.auto_partition:
                raise ValueError(
                    "--plan auto supersedes --auto-partition (it solves "
                    "the stage split AND the mix); drop one")
            owned = (
                ("--stages", self.num_stages, None),
                ("--dp-replicas", self.dp_replicas, 1),
                ("--tp-size", self.tp_size, 1),
                ("--stage-replication", self.stage_replication, None),
                ("--virtual-stages", self.virtual_stages, 1),
                ("--pipe-schedule", self.pipe_schedule, "fill-drain"),
                ("--pipe-costs", self.pipe_costs, "unit"),
                ("pipe_cost_vectors", self.pipe_cost_vectors, None),
                ("--plan-bounds", self.plan_bounds, None),
                ("--dp-shard-update", self.dp_shard_update, False),
                ("--update-interval", self.update_interval, 1),
            )
            clash = [name for name, val, dflt in owned if val != dflt]
            if clash:
                raise ValueError(
                    f"--plan auto owns the parallelism mix; leave "
                    f"{', '.join(clash)} unset (the planner chooses and "
                    f"records them in partition.json)")
        if self.plan_bounds is not None:
            if self.strategy not in ("gpipe", "pipedream"):
                raise ValueError(
                    "plan_bounds (explicit stage bounds) applies to the "
                    "pipeline strategies")
            if self.auto_partition:
                raise ValueError(
                    "--auto-partition solves the stage bounds; "
                    "--plan-bounds pins them — pick one")
            pb = tuple(int(x) for x in self.plan_bounds)
            chunks_n = self.resolved_stages() * max(1, self.virtual_stages)
            if len(pb) != chunks_n + 1:
                raise ValueError(
                    f"plan_bounds needs stages x virtual_stages + 1 = "
                    f"{chunks_n + 1} entries; got {len(pb)}")
            if pb[0] != 0 or any(a >= b for a, b in zip(pb, pb[1:])):
                raise ValueError(
                    f"plan_bounds must strictly increase from 0; got {pb}")
        if self.pipe_costs not in ("unit", "profile"):
            raise ValueError(
                f"unknown pipe_costs {self.pipe_costs!r} (choose unit or "
                f"profile)")
        if self.pipe_costs == "profile":
            if self.strategy != "gpipe":
                raise ValueError(
                    "pipe_costs='profile' (cost-weighted timetables) "
                    "applies to -f gpipe's schedule runtime")
            if not self.auto_partition:
                raise ValueError(
                    "pipe_costs='profile' needs --auto-partition (the "
                    "profile graph is where the per-chunk costs come from)")
            if self.pipe_schedule == "fill-drain":
                raise ValueError(
                    "pipe_costs='profile' needs an event schedule "
                    "(--pipe-schedule 1f1b/interleaved/zero-bubble/"
                    "zero-bubble-h2/searched); the fill-drain autodiff "
                    "scan executes the unit timetable by construction")
        if self.schedule_trace is not None:
            if self.strategy != "gpipe" or not self.auto_partition:
                raise ValueError(
                    "schedule_trace (measured-bubble schedule advice) "
                    "feeds -f gpipe's --auto-partition advisor; without "
                    "auto-partition there is no advice to fold it into")
        if self.pipe_cost_vectors is not None:
            if self.strategy != "gpipe":
                raise ValueError(
                    "pipe_cost_vectors applies to -f gpipe's schedule "
                    "runtime")
            if self.pipe_schedule == "fill-drain":
                raise ValueError(
                    "cost-weighted timetables execute on the EVENT "
                    "schedules (1f1b/interleaved/zero-bubble/"
                    "zero-bubble-h2/searched); the fill-drain autodiff "
                    "scan is lockstep by construction")
            from ddlbench_tpu.partition.schedule import normalize_costs

            normalize_costs(  # raises on malformed vectors
                self.pipe_cost_vectors,
                self.resolved_stages() * self.virtual_stages)
        if self.dp_shard_update and self.shard_opt_state:
            raise ValueError(
                "dp_shard_update supersedes shard_opt_state: the explicit "
                "engine already shards the optimizer state (pick one)")
        if self.shard_opt_state and self.strategy == "dp" and \
                self.resolved_allreduce_dtype() != "float32":
            raise ValueError(
                "shard_opt_state is a GSPMD placement knob; the compressed-"
                "allreduce engine pins the optimizer state replicated — "
                "use dp_shard_update for sharded state with bf16 wire")
        if self.resolved_allreduce_dtype() != "float32" and \
                self.strategy != "dp":
            raise ValueError(
                "allreduce_dtype applies to the dp strategy's gradient "
                "collectives")
        if self.dp_explicit_collectives():
            if "moe" in self.arch:
                raise ValueError(
                    "dp_shard_update / compressed allreduce run the train "
                    "step under shard_map, where MoE router statistics "
                    "would become per-shard (replicated dp routes over the "
                    "global batch); use replicated dp for MoE archs")
            if self.remat_layers:
                raise ValueError(
                    "remat_layers is incompatible with the explicit dp "
                    "collective engine (checkpointed traces cannot carry "
                    "the shard_map axis context); use replicated dp")
        if self.virtual_stages > 1:
            if self.strategy not in ("gpipe", "pipedream"):
                raise ValueError(
                    "virtual_stages (interleaved schedule) requires a "
                    "pipeline strategy (gpipe or pipedream)")
            s = self.resolved_stages()
            _, chunks = self.resolved_batches()
            if chunks % s:
                # gpipe's interleaved timetable groups microbatches by S;
                # pipedream's async variant inherits the constraint through
                # its synchronous interleaved eval pipeline
                raise ValueError(
                    f"interleaved schedule needs num_microbatches ({chunks}) "
                    f"divisible by stages ({s})")

    def replace(self, **kw: Any) -> "RunConfig":
        return dataclasses.replace(self, **kw)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Shape/policy configuration of the continuous-batching serving engine
    (serve/engine.py). Frozen + validated like :class:`RunConfig` — one
    config surface, constructible from servebench flags or a dict.

    The static-shape contract: every decode step is a [max_batch, 1] model
    call and every prefill chunk a [1, prefill_chunk] call, so the jit
    cache holds at most ``max_len / page`` variants of each (one per live
    page count) regardless of traffic.
    """

    max_batch: int = 8  # engine rows = concurrent requests per replica
    pool_pages: int = 64  # shared KV pool slots (slot 0 = scratch)
    page: int = 16  # positions per page (ops/paged_decode.py PAGE analog)
    max_len: int = 256  # per-request stream capacity (prompt + output)
    # tokens a step may process: active decode rows count 1 each, the
    # remainder is packed with prefill chunks. 0 = max_batch + 2 chunks.
    token_budget: int = 0
    # tokens per prefill call (page multiple); 0 = whole prompt in ONE
    # padded call ("unchunked admission" — one compile, more padding)
    prefill_chunk: int = 16
    policy: str = "continuous"  # "continuous" | "static" (the A/B baseline)
    replicas: int = 1  # data-parallel serving replicas (mesh 'data' axis)
    # tensor-parallel width of ONE replica (mesh 'model' axis): the serve
    # jitted programs shard Megatron-style over tp devices — each holds
    # its contiguous head group of every layer (tp_split_layer_params)
    # and its slice of the KV pool, sharing ONE page table — so a model
    # larger than one chip's HBM serves at all. tp=1 is the bitwise-
    # pinned single-chip path (the programs are literally unchanged).
    tp: int = 1
    # cross-request prefix cache (serve/prefix.py): admissions bind the
    # already-resident immutable KV pages of their longest cached prefix
    # and chunk-prefill only the uncached tail. Continuous policy only —
    # the static baseline measures cache-off scheduling by definition.
    prefix_cache: bool = False
    # sampling (0.0 = greedy argmax, the default — all greedy pins are
    # bitwise untouched). temperature > 0 samples from softmax(logits / T)
    # on the host with counter-based per-request seeds (fold sample_seed +
    # request id + token index), so streams are bitwise-reproducible per
    # seed and eviction/recompute regenerates identical tokens.
    temperature: float = 0.0
    top_k: int = 0  # 0 = full vocab; > 0 restricts sampling to the k best
    sample_seed: int = 0
    # request-lifecycle tracing (telemetry/): when True the engine emits
    # submit/queue_wait/admit/prefill_chunk/first_token/decode/evict/
    # recompute/finish events (one Chrome-trace track per request per
    # replica) plus per-step counter tracks into the process-global
    # tracer, stamped in VIRTUAL model-pass units. Metrics-neutral by
    # construction on AND off: tracing only records what the scheduler
    # already decided — token streams and virtual-time JSON are bitwise
    # identical either way (pinned, tests/test_serve_trace.py).
    trace: bool = False
    # flight recorder: ring of the most recent per-step engine states
    # (occupancy, queue depth, packer fill, ...) kept for
    # ``ServeEngine.snapshot()`` — the live-debug window into a serving
    # replica. 0 disables the ring; snapshot() still reports live state.
    flight_recorder: int = 64
    # SLOs in virtual time units, used by snapshot()'s
    # attainment-so-far (telemetry/stats.request_slo_ok). 0 = no SLO.
    # Scheduling NEVER reads these — they are observability-only.
    slo_ttft: float = 0.0
    slo_itl: float = 0.0
    # serve-side heartbeat (ISSUE 15): a replica that HOLDS WORK but makes
    # no scheduling progress for more than this many virtual time units is
    # declared a straggler by ReplicatedServer and drained — its in-flight
    # requests evict onto the recompute path and redistribute least-loaded
    # over the survivors, exactly like a scale-down (train/watchdog.py's
    # no-progress detector, re-used clockless via ProgressMonitor).
    # 0 disables detection (the default — single-replica engines and all
    # pre-chaos callers are bitwise unaffected).
    heartbeat: float = 0.0
    # KV-pool storage dtype (ops/paged_decode.py serve pool). "float32" is
    # the bitwise-pinned default; "bfloat16" halves pool bytes; "int8"
    # quarters them — pages quantize at the write boundary with a stored
    # per-page scale sidecar (unbiased stochastic rounding, counter-based
    # seeds, PR 6's EQuARX-lite machinery) and dequantization is fused
    # into the attention kernels/references. Output quality is pinned by
    # an accparity-style digits gate (tests/test_serve_quant.py).
    kv_dtype: str = "float32"
    # silent-data-corruption defense (serve/integrity.py): when True the
    # engine keeps a host-side crc32c ledger over every pool page's
    # payload + sidecar rows, stamped at the pool-write boundary and
    # verified at every trust boundary (handoff export/import, COW
    # source pages, prefix-hit binds, eviction-recompute). A mismatch
    # quarantines the slot (excluded from allocation for the rest of
    # the run) and recovers every referencing request through the
    # existing re-prefill path, which regenerates pages byte-identically
    # — so detected corruption never reaches a token stream. Off (the
    # default) is bitwise the pre-SDC engine: no ledger, no checks.
    integrity: bool = False
    # background scrub budget: verify up to this many resident stamped
    # pages per step (round-robin cursor), catching latent corruption on
    # cold prefix pages before a full-hit serves them. 0 disables the
    # scrubber; > 0 requires integrity (there is no ledger to check
    # against otherwise).
    scrub: int = 0
    # self-drafting speculative decoding: "none" (every decode pass emits
    # one token per row) or "ngram:N:K" — a host-side N-gram drafter
    # proposes up to K tokens per decode row from the row's own emitted
    # prefix, and ONE verify pass (a K+1-wide chunk call at per-row
    # starts) scores them all; the longest prefix matching greedy argmax
    # is accepted, rejected tail pages roll back like eviction. Greedy
    # only (acceptance compares argmaxes); spec-on greedy streams are
    # pinned BITWISE identical to spec-off (tests/test_serve_spec.py).
    speculative: str = "none"

    def npg_max(self) -> int:
        return -(-self.max_len // self.page)

    def spec_params(self) -> Optional[tuple]:
        """(ngram_n, draft_k) when speculative decoding is on, else None.
        ``validate`` rejects malformed specs; this parses a valid one."""
        if self.speculative == "none":
            return None
        _, n, k = self.speculative.split(":")
        return int(n), int(k)

    def resolved_token_budget(self) -> int:
        if self.token_budget:
            return self.token_budget
        return self.max_batch + 2 * self.resolved_prefill_chunk()

    def resolved_prefill_chunk(self) -> int:
        if self.prefill_chunk:
            return self.prefill_chunk
        return self.npg_max() * self.page  # whole-stream padded chunk

    def validate(self) -> None:
        if self.policy not in ("continuous", "static"):
            raise ValueError(
                f"policy must be continuous|static, got {self.policy!r}")
        if min(self.max_batch, self.page, self.max_len, self.replicas,
               self.tp) < 1:
            raise ValueError(
                "max_batch, page, max_len, replicas, and tp must be "
                "positive")
        if self.prefill_chunk < 0 or self.token_budget < 0:
            # 0 means "resolve a default" for both; negatives would pass
            # the modulo/starvation checks and crash the engine mid-run
            raise ValueError(
                "prefill_chunk and token_budget must be >= 0")
        if self.prefill_chunk and self.prefill_chunk % self.page:
            raise ValueError(
                f"prefill_chunk {self.prefill_chunk} must be a multiple of "
                f"the page size {self.page} (chunks are page-aligned)")
        if self.pool_pages < self.npg_max() + 1:
            raise ValueError(
                f"pool_pages {self.pool_pages} cannot hold one max-length "
                f"request ({self.npg_max()} pages) plus the scratch slot — "
                "a request that can never fit would evict itself forever")
        if self.resolved_token_budget() < self.resolved_prefill_chunk():
            raise ValueError(
                "token_budget below one prefill chunk starves admission "
                f"({self.resolved_token_budget()} < "
                f"{self.resolved_prefill_chunk()})")
        if self.prefix_cache and self.policy != "continuous":
            raise ValueError(
                "prefix_cache requires the continuous policy — the static "
                "baseline measures cache-off scheduling (run it cache-off)")
        if self.temperature < 0.0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = full vocab), got "
                             f"{self.top_k}")
        if self.top_k and self.temperature == 0.0:
            raise ValueError(
                "top_k without temperature has no sampling to restrict "
                "(greedy already takes the argmax)")
        if self.flight_recorder < 0:
            raise ValueError(
                f"flight_recorder must be >= 0 (0 disables the ring), "
                f"got {self.flight_recorder}")
        if self.slo_ttft < 0 or self.slo_itl < 0:
            raise ValueError(
                "slo_ttft and slo_itl must be >= 0 (0 = no SLO)")
        if self.heartbeat < 0:
            raise ValueError(
                f"heartbeat must be >= 0 time units (0 disables straggler "
                f"detection), got {self.heartbeat}")
        if self.scrub < 0:
            raise ValueError(
                f"scrub must be >= 0 pages/step (0 disables the "
                f"scrubber), got {self.scrub}")
        if self.scrub and not self.integrity:
            raise ValueError(
                "scrub without integrity has no checksum ledger to "
                "verify against — enable integrity or drop scrub")
        if self.kv_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"kv_dtype must be float32|bfloat16|int8, got "
                f"{self.kv_dtype!r}")
        if self.speculative != "none":
            parts = self.speculative.split(":")
            if len(parts) != 3 or parts[0] != "ngram":
                raise ValueError(
                    f"speculative must be 'none' or 'ngram:N:K', got "
                    f"{self.speculative!r}")
            try:
                n, k = int(parts[1]), int(parts[2])
            except ValueError:
                raise ValueError(
                    f"speculative ngram wants integer N:K, got "
                    f"{self.speculative!r}") from None
            if n < 1 or k < 1:
                raise ValueError(
                    f"speculative ngram needs N >= 1 and K >= 1, got "
                    f"N={n} K={k}")
            if self.temperature > 0.0:
                raise ValueError(
                    "speculative decoding is greedy-only (acceptance "
                    "compares draft tokens against greedy argmax); drop "
                    "temperature or speculative")
            if k + 1 > self.max_len:
                raise ValueError(
                    f"speculative draft width K+1 ({k + 1}) exceeds "
                    f"max_len {self.max_len}")

    def replace(self, **kw: Any) -> "ServeConfig":
        return dataclasses.replace(self, **kw)
