"""Fault spec grammar, the armed-spec registry, and the injection hooks.

Spec grammar (the ``--inject`` flag, repeatable)::

    KIND@EPOCH:STEP

``EPOCH`` is the 1-based training epoch and ``STEP`` the 0-based step index
within it — the same coordinates the (epoch, step)-addressed data pipeline
uses, so injections are deterministic and reproducible. Kinds:

* ``kill``           — SIGKILL this process at the step boundary *before*
                       dispatching (EPOCH, STEP). The hard-crash primitive:
                       no atexit handlers, no flushes, no cleanup — exactly
                       what the checkpoint commit protocol must survive.
* ``ckpt-corrupt``   — after the checkpoint save for (EPOCH, STEP) commits,
                       truncate + bit-flip bytes in that (newest) checkpoint.
                       Epoch-granular saves fire with STEP 0. Models silent
                       media corruption; ``latest_valid`` must detect it and
                       fall back.
* ``prefetch-die``   — raise inside the prefetch producer thread before it
                       fetches (EPOCH, STEP). Exercises the producer-death
                       propagation path (data/prefetch.py).
* ``nan-loss``       — poison the host-side loss of (EPOCH, STEP) with NaN.
                       Exercises the --nan-policy path without perturbing
                       device state.
* ``slow-host``      — sleep ``DDLB_FAULT_SLOWHOST_S`` (default 2.0) seconds
                       inside ``distributed.initialize()``, modeling a
                       slow-starting peer. EPOCH:STEP are parsed but unused
                       (the init path predates the step clock); use 0:0.
* ``preempt``        — SIGTERM this process at the step boundary *before*
                       dispatching (EPOCH, STEP): the deterministic twin of
                       a cluster eviction. With the guard's preemption
                       handler installed (checkpoint dir configured), the
                       loop commits a step-granular checkpoint and exits
                       with the distinct graceful code
                       (guard/preempt.py PREEMPT_EXIT_CODE).
* ``nan-grad``       — poison the DEVICE-side gradients of (EPOCH, STEP):
                       the loop NaNs that step's lr, and the guard-armed
                       engines carry the NaN into the backward through the
                       objective multiplier ``lr*0 + 1`` — so on-device
                       detection and the in-step skip-select are what get
                       exercised (unlike ``nan-loss``, which is host-only).
* ``grad-spike``     — multiply the HOST-observed grad norm of the window
                       containing (EPOCH, STEP) by ``DDLB_FAULT_SPIKE``
                       (default 1000.0): drives the EWMA spike detector and
                       its policy path without perturbing device state.
* ``shrink``/``grow`` — the in-process half of an elastic world RESHAPE
                       (ISSUE 12): SIGTERM at the (EPOCH, STEP) boundary,
                       exactly like ``preempt`` — the loop commits a
                       step-granular checkpoint (now carrying the logical
                       world-shape metadata) and exits gracefully. A
                       process cannot change its own device count; the
                       chaosbench supervisor (``--reshape``) matches the
                       distinct ``fault-inject: shrink/grow`` line and
                       relaunches the child at the new ``--devices`` with
                       ``--elastic-resume``, which is where the world
                       actually changes.

Each armed spec fires at most once per process. The registry is module
state: ``arm()`` installs specs (idempotent re-arm with the same specs is a
no-op), ``disarm()`` clears them. With nothing armed every hook returns
after one falsy check — the hot loop pays nothing.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import sys
import time
from typing import List, Optional, Sequence, Tuple

FAULT_KINDS = ("kill", "ckpt-corrupt", "prefetch-die", "nan-loss",
               "slow-host", "preempt", "nan-grad", "grad-spike",
               "shrink", "grow")

# Armed specs; empty = disarmed. Every hook checks this first.
_SPECS: List["FaultSpec"] = []


@dataclasses.dataclass
class FaultSpec:
    kind: str
    epoch: int
    step: int
    fired: bool = False

    def matches(self, epoch: int, step: int) -> bool:
        return (not self.fired and self.epoch == epoch and self.step == step)

    def __str__(self) -> str:
        return f"{self.kind}@{self.epoch}:{self.step}"


def parse_injections(specs: Sequence[str]) -> Tuple[FaultSpec, ...]:
    """Parse ``KIND@EPOCH:STEP`` specs; raises ValueError on bad grammar."""
    out = []
    for raw in specs:
        try:
            kind, at = raw.split("@", 1)
            ep_s, st_s = at.split(":", 1)
            epoch, step = int(ep_s), int(st_s)
        except ValueError:
            raise ValueError(
                f"bad --inject spec {raw!r}: expected KIND@EPOCH:STEP "
                f"(e.g. kill@2:5)")
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in --inject {raw!r} "
                f"(choose from {', '.join(FAULT_KINDS)})")
        if epoch < 0 or step < 0:
            raise ValueError(
                f"--inject {raw!r}: EPOCH and STEP must be >= 0")
        out.append(FaultSpec(kind, epoch, step))
    return tuple(out)


def arm(specs: Sequence[str]) -> None:
    """Install (parsed) fault specs, replacing whatever was armed before.

    Already-fired state is preserved across a re-arm with identical specs
    (run_benchmark re-arms what the CLI armed earlier in the same process;
    a fault must still fire only once).
    """
    parsed = parse_injections(specs)
    if [(s.kind, s.epoch, s.step) for s in parsed] == \
            [(s.kind, s.epoch, s.step) for s in _SPECS]:
        return
    _SPECS[:] = list(parsed)


def disarm() -> None:
    _SPECS.clear()


def armed_specs() -> Tuple[FaultSpec, ...]:
    return tuple(_SPECS)


def _take(kind: str, epoch: int, step: int) -> Optional[FaultSpec]:
    for s in _SPECS:
        if s.kind == kind and s.matches(epoch, step):
            s.fired = True
            return s
    return None


# ---- hooks (call sites: train/loop.py, train/checkpoint.py,
# ---- data/prefetch.py, distributed.py) ------------------------------------

def step_boundary(epoch: int, step: int) -> None:
    """Train-loop hook, called before dispatching (epoch, step).

    ``kill``: announce (flushed — the supervisor's MTTR clock reads it),
    then SIGKILL. SIGKILL and not sys.exit: the whole point is that no
    Python-level cleanup runs, so the commit protocol is what is tested.
    """
    if not _SPECS:
        return
    if _take("kill", epoch, step):
        print(f"fault-inject: kill at epoch {epoch} step {step}", flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)
    for kind in ("preempt", "shrink", "grow"):
        if _take(kind, epoch, step):
            # SIGTERM, not an exception: the graceful path under test IS
            # the signal handler -> flag -> boundary-check -> checkpoint
            # chain. Python delivers the signal before the next bytecode,
            # so the flag is visible to the check right after this hook.
            # shrink/grow print their own kind: the chaosbench supervisor
            # keys the world reshape off this exact line.
            print(f"fault-inject: {kind} (SIGTERM) at epoch {epoch} step "
                  f"{step}", flush=True)
            os.kill(os.getpid(), signal.SIGTERM)
            break


def poison_loss(epoch: int, step: int) -> bool:
    """True when (epoch, step)'s host-side loss should be replaced with NaN."""
    if not _SPECS:
        return False
    spec = _take("nan-loss", epoch, step)
    if spec:
        print(f"fault-inject: nan-loss at epoch {epoch} step {step}",
              flush=True)
        return True
    return False


def poison_grad(epoch: int, step: int) -> bool:
    """True when (epoch, step)'s DEVICE gradients should be poisoned (the
    loop NaNs the step's lr; guard-armed engines carry it into the
    backward — see the ``nan-grad`` grammar entry)."""
    if not _SPECS:
        return False
    if _take("nan-grad", epoch, step):
        print(f"fault-inject: nan-grad at epoch {epoch} step {step}",
              flush=True)
        return True
    return False


def spike_grad(epoch: int, step_lo: int, step_hi: int) -> float:
    """Multiplier for the host-observed grad norm of the window covering
    0-based steps [step_lo, step_hi] (the guard syncs health once per log
    interval, so the spec fires when its step falls inside the window)."""
    if not _SPECS:
        return 1.0
    for s in _SPECS:
        if (s.kind == "grad-spike" and not s.fired and s.epoch == epoch
                and step_lo <= s.step <= step_hi):
            s.fired = True
            factor = float(os.environ.get("DDLB_FAULT_SPIKE", "1000.0"))
            print(f"fault-inject: grad-spike x{factor:g} at epoch {epoch} "
                  f"step {s.step}", flush=True)
            return factor
    return 1.0


def prefetch_producer(epoch: int, step: int) -> None:
    """Producer-thread hook (data/prefetch.py), before fetching (epoch, step)."""
    if not _SPECS:
        return
    if _take("prefetch-die", epoch, step):
        raise RuntimeError(
            f"fault-inject: prefetch producer killed at epoch {epoch} "
            f"step {step}")


def checkpoint_saved(path: str, epoch: int, step: Optional[int]) -> None:
    """Post-commit hook (train/checkpoint.py). Epoch-granular saves match
    STEP 0 specs (they carry no interior step)."""
    if not _SPECS:
        return
    if _take("ckpt-corrupt", epoch, step if step is not None else 0):
        corrupt_checkpoint(path)


def multihost_init() -> None:
    """distributed.initialize() hook: the slow-host delay."""
    if not _SPECS:
        return
    if _take("slow-host", *_first_pending("slow-host")):
        delay = float(os.environ.get("DDLB_FAULT_SLOWHOST_S", "2.0"))
        print(f"fault-inject: slow-host sleeping {delay:.1f}s in multihost "
              f"init", flush=True)
        time.sleep(delay)


def _first_pending(kind: str) -> Tuple[int, int]:
    """(epoch, step) of the first unfired spec of ``kind`` — used by hooks
    at sites that predate the step clock (multihost init), so their specs
    fire regardless of the coordinates they were written with."""
    for s in _SPECS:
        if s.kind == kind and not s.fired:
            return s.epoch, s.step
    return -1, -1


def corrupt_checkpoint(path: str) -> List[str]:
    """Truncate + bit-flip bytes in a checkpoint directory (or file).

    Damages the largest NON-MARKER file under ``path`` (the array data — a
    damaged COMMIT marker is the trivially-detected case; the manifest
    verification must catch damage to the payload): flips one byte in the
    middle and truncates the tail — both silent-media-corruption shapes
    ``latest_valid`` must catch. Returns the damaged file paths.
    """
    targets = []
    if os.path.isfile(path):
        targets = [path]
    else:
        best, best_size = None, -1
        for root, _, files in os.walk(path):
            for name in files:
                if name == "COMMIT.json":
                    continue
                p = os.path.join(root, name)
                try:
                    size = os.path.getsize(p)
                except OSError:
                    continue
                if size > best_size:
                    best, best_size = p, size
        if best is not None:
            targets = [best]
    damaged = []
    for p in targets:
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            if size > 0:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
            f.truncate(max(1, size - max(1, size // 4)))
        damaged.append(p)
        print(f"fault-inject: ckpt-corrupt damaged {p}", flush=True)
    return damaged
