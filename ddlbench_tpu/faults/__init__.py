"""Deterministic fault injection for robustness benchmarking.

The reference suite has no failure story at all (SURVEY.md §5.3: a 2-hour
process-group timeout and a pkill cleanup script); nothing in it can *prove*
that a kill mid-run recovers. This package is the injection half of the
fault-tolerance subsystem: a registry of host-side faults armed from
``--inject KIND@EPOCH:STEP`` specs (repeatable), fired from hooks in
``train/loop.py`` (step boundaries, loss poisoning), ``train/checkpoint.py``
(post-commit corruption), ``data/prefetch.py`` (producer death), and
``distributed.py`` (multihost init delay). ``tools/chaosbench.py`` drives a
kill/restart supervisor over these faults and measures recovery.

Zero-cost contract: with the registry empty (the default), every hook is a
single module-attribute truthiness check and an immediate return — no
allocation, no parsing, no clock reads on the hot path.

Determinism contract: faults address the same ``(epoch, step)`` coordinates
the data pipeline uses, so an injected run is reproducible — the same spec
always fires at the same point of the same trajectory. Each spec fires at
most once per process.

See :mod:`ddlbench_tpu.faults.registry` for the spec grammar and kinds.
"""

from ddlbench_tpu.faults.registry import (  # noqa: F401
    FAULT_KINDS,
    FaultSpec,
    arm,
    armed_specs,
    checkpoint_saved,
    corrupt_checkpoint,
    disarm,
    multihost_init,
    parse_injections,
    poison_grad,
    poison_loss,
    prefetch_producer,
    spike_grad,
    step_boundary,
)
