"""LM-workload single-chip microbenchmark: tokens/sec across optimization
knobs.

Sweeps the training step of a token workload over the framework's two kernel
knobs — attention backend (Pallas flash vs XLA) and the fused LM-head loss
(ops/fused_xent.py) vs full-logits — so the kernel wins can be quantified on
real hardware in one command. The CNN analog is bench.py (the headline
driver-recorded number); this is the transformer-side companion used for
PERF.md measurements.

Each configuration prints one JSON line:

    {"config": "flash+fused", "tokens_per_sec": N, "ms_per_step": N, ...}

Sync discipline follows bench.py: chain the train state through all steps and
sync via float(metric) (device transfer), which is reliable on the axon TPU
tunnel where block_until_ready can return early.

Usage:
    python -m ddlbench_tpu.tools.lmbench [-m transformer_s] [-b synthtext]
        [--batch-size 16] [--steps 20] [--dtype bfloat16] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", default="transformer_s")
    p.add_argument("-b", "--benchmark", default="synthtext")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--label-smoothing", type=float, default=None)
    p.add_argument("--configs", default=None,
                   help="comma list among flash+fused,flash+logits,"
                        "xla+fused,xla+logits,auto (default: the four "
                        "forced cells; 'auto' measures the length-based "
                        "dispatch a default run gets)")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.distributed import (backend_provenance,
                                          enable_compilation_cache,
                                          warn_cpu_fallback)

    enable_compilation_cache()
    # actual-backend record on every row + loud cpu-fallback banner (shared
    # classification — distributed.backend_provenance), matching
    # bench.py/scalebench: a silent cpu fallback must never read as a chip
    # number in the PERF.md trail
    prov = backend_provenance(args.platform)
    warn_cpu_fallback(prov, "lmbench")

    from ddlbench_tpu.config import DATASETS, RunConfig
    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.distributed import is_tpu_backend
    from ddlbench_tpu.models.transformer import set_attention_backend
    from ddlbench_tpu.parallel.api import make_strategy

    token_benchmarks = sorted(
        n for n, s in DATASETS.items() if s.kind in ("tokens", "seq2seq"))
    if (args.benchmark not in DATASETS
            or DATASETS[args.benchmark].kind not in ("tokens", "seq2seq")):
        p.error(f"-b {args.benchmark!r} is not a token workload; lmbench "
                f"sweeps token workloads (pick one of {token_benchmarks})")

    all_configs = {
        "flash+fused": ("flash", True),
        "flash+logits": ("flash", False),
        "xla+fused": ("xla", True),
        "xla+logits": ("xla", False),
        # what a default run actually gets: the length-based dispatch
        # (models/transformer.py FLASH_AUTO_MIN_SEQ) + fused head. Not in
        # the default sweep (it duplicates one of the forced cells); use
        # --configs auto to check the dispatch picks the winning backend.
        "auto": ("auto", True),
    }
    on_tpu = is_tpu_backend()
    if args.configs:
        names = [c.strip() for c in args.configs.split(",") if c.strip()]
        unknown = [c for c in names if c not in all_configs]
        if unknown:
            p.error(f"unknown --configs {unknown}; choose from "
                    f"{sorted(all_configs)}")
    else:
        # flash off-TPU means interpret mode (minutes per step) — skip it
        sweep = [n for n in all_configs if n != "auto"]
        names = sweep if on_tpu else ["xla+fused", "xla+logits"]

    def run_config(name: str, remat: bool):
        attn, fused = all_configs[name]
        cfg = RunConfig(
            benchmark=args.benchmark,
            strategy="single",
            arch=args.model,
            batch_size=args.batch_size,
            compute_dtype=args.dtype,
            attention_backend=attn,
            fused_head_loss=fused,
            remat_layers=remat,
            label_smoothing=args.label_smoothing,
            steps_per_epoch=args.steps,
        )
        strategy = make_strategy(cfg)
        spec = cfg.dataset()
        B = cfg.global_batch()
        data = make_synthetic(spec, B, steps_per_epoch=args.steps)
        ts = strategy.init(jax.random.key(cfg.seed))
        lr = jnp.float32(cfg.resolved_lr())

        from ddlbench_tpu.tools.timing import timed_steps

        def run_step(x, y, _s=strategy):
            nonlocal ts
            ts, m = _s.train_step(ts, x, y, lr)
            return m

        dt = timed_steps(run_step, data.batch, args.steps, args.warmup)

        tokens = args.steps * B * spec.seq_len
        print(json.dumps({
            "config": name,
            "model": args.model,
            "benchmark": args.benchmark,
            "batch": B,
            "seq_len": spec.seq_len,
            "remat": remat,
            "tokens_per_sec": round(tokens / dt, 1),
            "ms_per_step": round(1000 * dt / args.steps, 2),
            **prov,
        }), flush=True)

    def is_oom(e: BaseException) -> bool:
        msg = str(e)
        return ("RESOURCE_EXHAUSTED" in msg or "Ran out of memory" in msg
                or "out of memory" in msg.lower())

    ok = 0
    for name in names:
        # An OOM in one configuration must not lose the others' numbers
        # (measured on-chip: at T=8192 the XLA-attention configs exceed one
        # v5e's HBM — every layer's [B, H, T, T] score matrix stays live into
        # the backward — while the flash configs fit). Record the OOM as a
        # data point, then retry that cell with per-layer rematerialization
        # (cfg.remat_layers), which caps live activations at one layer.
        # MoE archs cannot remat (config.validate: the router aux-loss side
        # channel cannot escape a checkpointed trace) — no retry for them.
        attempts = (False,) if "moe" in args.model else (False, True)
        for remat in attempts:
            try:
                run_config(name, remat)
                ok += 1
                break
            except Exception as e:  # noqa: BLE001 — sweep must survive a cell
                if not is_oom(e):
                    raise
                print(json.dumps({
                    "config": name, "model": args.model,
                    "benchmark": args.benchmark, "remat": remat,
                    "error": "hbm-oom",
                    "detail": str(e).splitlines()[0][:200],
                    **prov,
                }), flush=True)
            finally:
                # reset the backend override for the next config
                set_attention_backend("auto")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
