"""Explicit real-dataset import CLI.

Converts a standard on-disk dataset (ImageFolder JPEG tree, MNIST IDX
archives, or CIFAR-10 python batches) into the native raw store the -s data
path serves from (data/imagefolder.py does the same lazily on first use).

Usage:
    python -m ddlbench_tpu.tools.import_data -b mnist --src /path/to/MNIST \\
        --dest /path/to/datadir
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-b", "--benchmark", required=True)
    p.add_argument("--src", required=True, help="real dataset root")
    p.add_argument("--dest", required=True,
                   help="data_dir the benchmarks will use (--data-dir)")
    p.add_argument("--splits", default="train,test")
    args = p.parse_args(argv)

    from ddlbench_tpu.config import DATASETS
    from ddlbench_tpu.data import imagefolder as imf

    if args.benchmark not in DATASETS:
        p.error(f"unknown benchmark {args.benchmark!r}")
    spec = DATASETS[args.benchmark]
    if spec.kind != "image":
        p.error("import supports image benchmarks (token workloads are "
                "synthetic streams)")
    import os

    for raw_split in args.splits.split(","):
        try:
            split = imf.normalize_split(raw_split)
        except ValueError as e:
            p.error(str(e))
        out = os.path.join(args.dest, spec.name, split)
        done = imf.detect_and_import(args.src, spec, split, out)
        if not done:
            print(f"error: no recognizable {split} data under {args.src}",
                  file=sys.stderr)
            return 1
        print(f"imported {split} -> {done}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
