"""Schedule-quality harness: every timetable priced on a (S, M, V, costs)
grid, with the searched-packer audit gate.

For each (stages, microbatches, virtual-stages) shape x cost profile this
tool builds EVERY shipped schedule's timetable (partition/schedule.py,
including the searched packer of partition/schedule_search.py) and prints
one JSON row per point:

    {"S": 3, "M": 6, "V": 1, "profile": "spike",
     "schedules": {"1f1b": {"bubble": N, "makespan": N}, ...},
     "heuristic_min_bubble": N, "searched_bubble": N, "searched_win": N}

``heuristic_min_bubble`` is the min over the pre-search family
(SEARCH_SEED_SCHEDULES: 1f1b and zero-bubble — the min-of-two the factory
shipped before the searched packer existed); ``searched_win`` is
heuristic_min - searched (>= 0 by construction, > 0 where the search found
a genuinely better packing). zero-bubble-h2 rows also carry the
steady-state period vs the linear makespan (its bubble IS the steady
figure; bubble_is_estimate).

**Audit gate** (the tools/servechaos.py requests_lost==0 pattern): if the
searched table's bubble exceeds the heuristic min on ANY point — the
seeded search regressed, which its construction forbids — the summary row
says so and the exit code is nonzero.

Pure host math: no devices are touched, rows are bitwise-reproducible
(fixed search budget + seed). Tier-1 smokes the tiny default grid through
main(); bigger sweeps ride --runslow (tests/test_schedule_costs.py).

Usage:
    python -m ddlbench_tpu.tools.schedbench \
        [--shapes 2:4:1,3:6:1,4:8:1] [--profiles unit,spike,ramp,valley,tilt] \
        [--budget 256] [--seed 0] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys

DEFAULT_SHAPES = "2:4:1,3:6:1,4:8:1,2:4:2"
DEFAULT_PROFILES = "unit,spike,ramp,valley,tilt"

# deterministic per-chunk cost templates, parameterized only by the chunk
# count (no rng: a profile name + shape IS the fixture)
_PROFILES = {
    "unit": lambda C: None,
    # one chunk an order of magnitude heavier — the [1,1,10,1]-style
    # bottleneck fixture of the uneven-cost acceptance suite
    "spike": lambda C: (tuple(10 if c == C // 2 else 1 for c in range(C)),
                        tuple(10 if c == C // 2 else 1 for c in range(C)),
                        (1,) * C),
    # smoothly skewed F/B/W, phase-shifted per kind
    "ramp": lambda C: (tuple(c % 3 + 1 for c in range(C)),
                       tuple((c + 1) % 3 + 1 for c in range(C)),
                       tuple((c + 2) % 3 + 1 for c in range(C))),
    # cheap middle, heavy ends with a heavy W tail — the shape the
    # strictly-better searched fixtures come from (heuristics commit the
    # first device before seeing the tail's W pressure)
    "valley": lambda C: (tuple(3 if c == 0 else 1 for c in range(C)),
                         tuple(2 + (c % 2) for c in range(C)),
                         tuple(4 if c == C - 1 else 1 for c in range(C))),
    # F shrinks down the ring while the LAST stage owns a heavy W: the
    # greedy heuristics pack the early stages' W eagerly and eat the tail
    # stall — at C=3 this is exactly the ((3,2,1),(2,3,1),(1,1,4)) fixture
    # the searched packer strictly beats (tests/test_schedule_costs.py)
    "tilt": lambda C: (tuple(max(1, 3 - c % 3) for c in range(C)),
                       tuple((2, 3, 1)[c % 3] for c in range(C)),
                       tuple(4 if c == C - 1 else 1 for c in range(C))),
}


def bench_point(S: int, M: int, V: int, profile: str, budget: int,
                seed: int) -> dict:
    """One (shape, profile) row: every schedule's bubble + makespan."""
    from ddlbench_tpu.partition.schedule import (PIPE_SCHEDULES,
                                                 SEARCH_SEED_SCHEDULES,
                                                 make_timetable)

    costs = _PROFILES[profile](S * V)
    row = {"S": S, "M": M, "V": V, "profile": profile,
           "budget": budget, "seed": seed, "schedules": {}}
    for name in PIPE_SCHEDULES:
        if V > 1 and M % S and name != "fill-drain":
            continue  # event schedules group microbatches in rounds of S
        tt = make_timetable(name, S, M, V, costs, search_budget=budget,
                            search_seed=seed)
        ent = {"bubble": round(tt.bubble_fraction(), 4),
               "makespan": tt.half_ticks}
        if tt.deferred_w:
            ent["steady_period"] = tt.steady_period()
            ent["deferred_w"] = len(tt.deferred_w)
        row["schedules"][name] = ent
    sch = row["schedules"]
    if "searched" in sch:
        hmin = min(sch[n]["bubble"] for n in SEARCH_SEED_SCHEDULES
                   if n in sch)
        row["heuristic_min_bubble"] = hmin
        row["searched_bubble"] = sch["searched"]["bubble"]
        row["searched_win"] = round(hmin - sch["searched"]["bubble"], 4)
    return row


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shapes", default=DEFAULT_SHAPES,
                   help="comma list of S:M[:V] shapes to sweep")
    p.add_argument("--profiles", default=DEFAULT_PROFILES,
                   help=f"comma list of cost profiles "
                        f"({', '.join(_PROFILES)})")
    p.add_argument("--budget", type=int, default=256,
                   help="searched-packer move-evaluation budget")
    p.add_argument("--seed", type=int, default=0,
                   help="searched-packer shift-move rng seed")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    from ddlbench_tpu.distributed import record_provenance

    prov = record_provenance(args.platform, "schedbench")
    print(json.dumps({"provenance": {**prov,
                                     "platform_arg": args.platform}}),
          flush=True)
    rows = []
    regressions = []
    for shape in args.shapes.split(","):
        parts = [int(v) for v in shape.strip().split(":")]
        S, M = parts[0], parts[1]
        V = parts[2] if len(parts) > 2 else 1
        for profile in args.profiles.split(","):
            profile = profile.strip()
            if profile not in _PROFILES:
                raise SystemExit(f"unknown cost profile {profile!r} "
                                 f"(choose from {', '.join(_PROFILES)})")
            row = bench_point(S, M, V, profile, args.budget, args.seed)
            row = {**row, "schema_version": prov["schema_version"]}
            print(json.dumps(row), flush=True)
            rows.append(row)
            if row.get("searched_win", 0) < 0:
                regressions.append(
                    f"S={S} M={M} V={V} {profile}: searched "
                    f"{row['searched_bubble']} > heuristic min "
                    f"{row['heuristic_min_bubble']}")
    gated = [r for r in rows if "searched_win" in r]
    wins = sum(1 for r in gated if r["searched_win"] > 0)
    print(json.dumps({
        "summary": {
            "points": len(rows),
            "gated_points": len(gated),
            "searched_strict_wins": wins,
            "regressions": regressions,
        }}), flush=True)
    if regressions:
        print(json.dumps({"error": "searched packer regressed below the "
                                   "heuristic min (see regressions)"}),
              flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
