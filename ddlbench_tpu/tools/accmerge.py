"""Merge accparity JSON documents (tools/accparity.py output).

A long matrix can lose single engines to per-engine timeouts on a
contended host; re-running ONLY those engines (same --data-dir, same
protocol) and merging is cheaper than repeating the whole matrix. Later
documents override earlier ones per engine; error rows are replaced by
successful re-runs. The summary block (final_accuracies / spread / pass)
is recomputed over the merged engine set with the FIRST document's
thresholds, and the protocol fields are carried from the first document —
callers must only merge runs of the same protocol.

Usage:
    python -m ddlbench_tpu.tools.accmerge a.json b.json [...] > merged.json
"""

from __future__ import annotations

import json
import sys


def merge(docs: list[dict]) -> dict:
    base = dict(docs[0])
    engines: dict = {}
    for doc in docs:
        for name, row in doc["engines"].items():
            if name in engines and "final_accuracy" in engines[name] \
                    and "final_accuracy" not in row:
                continue  # never replace a success with an error
            engines[name] = row
    finals = {n: e["final_accuracy"] for n, e in engines.items()
              if "final_accuracy" in e}
    spread = (max(finals.values()) - min(finals.values())) if finals else None
    base["engines"] = engines
    base["final_accuracies"] = finals
    base["final_spread"] = spread
    base["pass"] = (len(finals) == len(engines)
                    and all(v >= base["threshold"] for v in finals.values())
                    and spread is not None
                    and spread <= base["max_spread"])
    base["merged_from"] = len(docs)
    return base


def main(argv=None) -> int:
    paths = argv if argv is not None else sys.argv[1:]
    if len(paths) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    print(json.dumps(merge(docs)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
