"""Merge accparity JSON documents (tools/accparity.py output).

A long matrix can lose single engines to per-engine timeouts on a
contended host; re-running ONLY those engines (same --data-dir, same
protocol) and merging is cheaper than repeating the whole matrix. Later
documents override earlier ones per engine; error rows are replaced by
successful re-runs. The summary block (final_accuracies / spread / pass)
is recomputed over the merged engine set with the FIRST document's
thresholds, and the protocol fields are carried from the first document —
callers must only merge runs of the same protocol.

``--drop-unresolved`` removes engines whose merged row is still an error
(e.g. variants deliberately not re-run), moving them to a ``dropped``
record so the omission is explicit in the artifact rather than silent.

Usage:
    python -m ddlbench_tpu.tools.accmerge [--drop-unresolved]
        a.json b.json [...] > merged.json
"""

from __future__ import annotations

import json
import sys


def merge(docs: list[dict], drop_unresolved: bool = False) -> dict:
    base = dict(docs[0])
    engines: dict = {}
    for doc in docs:
        for name, row in doc["engines"].items():
            if name in engines and "final_accuracy" in engines[name] \
                    and "final_accuracy" not in row:
                continue  # never replace a success with an error
            engines[name] = row
    if drop_unresolved:
        dropped = {n: e for n, e in engines.items()
                   if "final_accuracy" not in e}
        if dropped:
            engines = {n: e for n, e in engines.items() if n not in dropped}
            base["dropped"] = dropped
    finals = {n: e["final_accuracy"] for n, e in engines.items()
              if "final_accuracy" in e}
    spread = (max(finals.values()) - min(finals.values())) if finals else None
    base["engines"] = engines
    base["final_accuracies"] = finals
    base["final_spread"] = spread
    base["pass"] = (len(finals) == len(engines)
                    and all(v >= base["threshold"] for v in finals.values())
                    and spread is not None
                    and spread <= base["max_spread"])
    base["merged_from"] = len(docs)
    return base


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:])
    drop = "--drop-unresolved" in paths
    if drop:
        paths.remove("--drop-unresolved")
    if len(paths) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    print(json.dumps(merge(docs, drop_unresolved=drop)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
