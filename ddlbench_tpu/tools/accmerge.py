"""Merge accparity JSON documents (tools/accparity.py output).

A long matrix can lose single engines to per-engine timeouts on a
contended host; re-running ONLY those engines (same --data-dir, same
protocol) and merging is cheaper than repeating the whole matrix. Later
documents override earlier ones per engine; error rows are replaced by
successful re-runs. The summary block (final_accuracies / spread / pass)
is recomputed over the merged engine set with the FIRST document's
thresholds, and the protocol fields are carried from the first document.
Protocol identity is ENFORCED: documents disagreeing on arch / threshold /
max_spread / protocol / dataset are different experiments and refuse to
merge (exit 2), so a stale matrix cannot silently override a newer re-run.

``--drop-unresolved`` removes engines whose merged row is still an error
(e.g. variants deliberately not re-run), moving them to a ``dropped``
record so the omission is explicit in the artifact rather than silent.

Usage:
    python -m ddlbench_tpu.tools.accmerge [--drop-unresolved]
        a.json b.json [...] > merged.json
"""

from __future__ import annotations

import json
import sys


# Fields that define the measurement protocol: documents disagreeing on any
# of these are different experiments, and merging them would let a stale
# matrix silently override a newer re-run (advisor r5). ``protocol`` itself
# is prose (epochs/lr/batch live in it), so it participates too.
PROTOCOL_FIELDS = ("arch", "threshold", "max_spread", "protocol", "dataset")


class ProtocolMismatch(ValueError):
    pass


def check_protocol(docs: list[dict]) -> None:
    """Raise ProtocolMismatch when any input disagrees with the first
    document on a protocol-identity field (missing fields are tolerated —
    older artifacts predate some of them)."""
    base = docs[0]
    for i, doc in enumerate(docs[1:], start=1):
        for field in PROTOCOL_FIELDS:
            if field in base and field in doc and doc[field] != base[field]:
                raise ProtocolMismatch(
                    f"document {i} disagrees with document 0 on protocol "
                    f"field {field!r}: {doc[field]!r} != {base[field]!r}; "
                    f"only re-runs of the SAME protocol may be merged")


def merge(docs: list[dict], drop_unresolved: bool = False) -> dict:
    check_protocol(docs)
    base = dict(docs[0])
    engines: dict = {}
    for doc in docs:
        for name, row in doc["engines"].items():
            if name in engines and "final_accuracy" in engines[name] \
                    and "final_accuracy" not in row:
                continue  # never replace a success with an error
            engines[name] = row
    if drop_unresolved:
        dropped = {n: e for n, e in engines.items()
                   if "final_accuracy" not in e}
        if dropped:
            engines = {n: e for n, e in engines.items() if n not in dropped}
            base["dropped"] = dropped
    finals = {n: e["final_accuracy"] for n, e in engines.items()
              if "final_accuracy" in e}
    spread = (max(finals.values()) - min(finals.values())) if finals else None
    base["engines"] = engines
    base["final_accuracies"] = finals
    base["final_spread"] = spread
    base["pass"] = (len(finals) == len(engines)
                    and all(v >= base["threshold"] for v in finals.values())
                    and spread is not None
                    and spread <= base["max_spread"])
    base["merged_from"] = len(docs)
    return base


def main(argv=None) -> int:
    paths = list(argv if argv is not None else sys.argv[1:])
    drop = "--drop-unresolved" in paths
    if drop:
        paths.remove("--drop-unresolved")
    if len(paths) < 2:
        print(__doc__, file=sys.stderr)
        return 2
    docs = []
    for p in paths:
        with open(p) as f:
            docs.append(json.load(f))
    try:
        merged = merge(docs, drop_unresolved=drop)
    except ProtocolMismatch as e:
        print(f"accmerge: {e}", file=sys.stderr)
        return 2
    print(json.dumps(merged))
    return 0


if __name__ == "__main__":
    sys.exit(main())
