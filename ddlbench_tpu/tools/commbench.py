"""Collective bandwidth microbenchmark.

Parity target: the reference's communication tests measure allreduce
bandwidth over tensor sizes 10..1e8 as two localhost gloo ranks
(pipedream-fork/runtime/tests/communication/all_to_all.py:42-59). Here the
same sweep runs over a real device mesh with XLA collectives — psum
(allreduce), all_gather, ppermute (the pipeline edge transfer), and
all_to_all (the EP dispatch) — so the numbers are the actual ICI/DCN rates
the strategies see.

Each timing chains the collective output into the next iteration's input
(out -> in dependency), which defeats dispatch caching/overlap and measures
real sequential executions — necessary on the axon TPU tunnel, where timing
repeated identical dispatches reports impossible (>peak) rates.

Output: one JSON line per (collective, size) with seconds/op and the
algorithmic bandwidth GB/s = payload_bytes / time (payload = the per-device
shard). Usage:

    python -m ddlbench_tpu.tools.commbench -g 8 [--platform cpu] \
        [--sizes 1e4,1e6,1e8] [--collectives psum,all_gather,ppermute,all_to_all]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _mesh_and_shardings(n, axis="x", devices=None):
    # topology-aware ordering (ICI neighbor rings) via the shared constructor,
    # so the reported bandwidth matches what the strategies' meshes see
    from ddlbench_tpu.distributed import make_mesh

    return make_mesh([(axis, n)], devices=devices)


def _make_collective(name: str, mesh, n: int):
    """Return (fn(local_array) -> local_array, payload_scale) shard_map'd over
    the mesh. payload_scale converts the per-device shard bytes into the
    bytes each device actually moves for the algorithmic-bandwidth figure."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ddlbench_tpu.parallel.gpipe import _shard_map as shard_map

    axis = mesh.axis_names[0]

    if name == "psum":
        def op(x):
            return lax.psum(x, axis)
        # ring allreduce moves 2*(n-1)/n of the buffer per device
        scale = 2.0 * (n - 1) / n
        in_spec, out_spec = P(axis), P(axis)
    elif name == "all_gather":
        def op(x):
            return lax.all_gather(x, axis, tiled=True)
        # each device receives the other n-1 shards
        scale = float(n - 1)
        # out kept "varying" (concatenated globally) so the VMA checker is
        # happy on every shard_map version; the timing is unaffected
        in_spec, out_spec = P(axis), P(axis)
    elif name == "ppermute":
        def op(x):
            return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])
        scale = 1.0
        in_spec, out_spec = P(axis), P(axis)
    elif name == "all_to_all":
        def op(x):
            return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        scale = (n - 1) / n
        in_spec, out_spec = P(axis), P(axis)
    else:
        raise ValueError(f"unknown collective {name!r}")

    fn = shard_map(op, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return fn, scale, in_spec


def bench_collective(name: str, mesh, n: int, size_floats: int,
                     iters: int = 10):
    """Time one collective at the given GLOBAL element count; returns a dict."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    fn, scale, in_spec = _make_collective(name, mesh, n)
    # round the per-device shard up to a multiple of n, so all_to_all can
    # split the local shard n ways too (global size = multiple of n^2)
    per_dev = max(1, (size_floats + n - 1) // n)
    per_dev = ((per_dev + n - 1) // n) * n
    global_n = per_dev * n
    x = jax.device_put(
        jax.numpy.ones((global_n,), jax.numpy.float32),
        NamedSharding(mesh, in_spec),
    )

    def chained(x0):
        def step(c, _):
            # fold the output into the carry — the dependency defeats
            # dispatch caching. all_gather's output is the concatenation of
            # every shard (n x larger); slice it back to the carry shape.
            out = fn(c)
            if out.shape != c.shape:
                out = out[: c.shape[0]]
            return c + 0.0 * out, None
        return lax.scan(step, x0, None, length=iters)[0]

    run = jax.jit(chained)
    jax.block_until_ready(run(x))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(run(x))
    dt = (time.perf_counter() - t0) / iters

    shard_bytes = per_dev * 4
    moved = shard_bytes * scale
    return {
        "collective": name,
        "global_floats": global_n,
        "shard_bytes": shard_bytes,
        "sec_per_op": dt,
        "algbw_gbps": moved / dt / 1e9,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="commbench", description=__doc__)
    p.add_argument("-g", "--devices", type=int, default=None)
    p.add_argument("--collectives",
                   default="psum,all_gather,ppermute,all_to_all")
    p.add_argument("--sizes", default="1e4,1e5,1e6,1e7,1e8",
                   help="global float32 counts (reference sweep: 10..1e8)")
    p.add_argument("--iters", type=int, default=10)
    from ddlbench_tpu.distributed import add_platform_arg

    add_platform_arg(p)
    args = p.parse_args(argv)

    import jax

    from ddlbench_tpu.distributed import apply_platform, force_host_mesh_platform

    if args.platform:
        apply_platform(args.platform)
    else:
        force_host_mesh_platform()

    n = args.devices or len(jax.devices())
    mesh = _mesh_and_shardings(n)
    for name in args.collectives.split(","):
        for size in args.sizes.split(","):
            r = bench_collective(name.strip(), mesh, n, int(float(size)),
                                 args.iters)
            print(json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
