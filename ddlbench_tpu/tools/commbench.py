"""Collective bandwidth microbenchmark.

Parity target: the reference's communication tests measure allreduce
bandwidth over tensor sizes 10..1e8 as two localhost gloo ranks
(pipedream-fork/runtime/tests/communication/all_to_all.py:42-59). Here the
same sweep runs over a real device mesh with XLA collectives — psum
(allreduce), all_gather, ppermute (the pipeline edge transfer), and
all_to_all (the EP dispatch) — so the numbers are the actual ICI/DCN rates
the strategies see.

Each timing chains the collective output into the next iteration's input
(out -> in dependency), which defeats dispatch caching/overlap and measures
real sequential executions — necessary on the axon TPU tunnel, where timing
repeated identical dispatches reports impossible (>peak) rates.

Output: one JSON line per (collective, size) with seconds/op and the
algorithmic bandwidth GB/s = payload_bytes / time (payload = the per-device
shard). Usage:

    python -m ddlbench_tpu.tools.commbench -g 8 [--platform cpu] \
        [--sizes 1e4,1e6,1e8] \
        [--collectives psum,all_gather,reduce_scatter,ppermute,all_to_all] \
        [--buckets 1,4,8]

``--buckets`` sweeps the BUCKETED variant (one collective per contiguous
chunk of the same payload) — the wire-level cost model for the dp engine's
``--comm-buckets`` comm/compute overlap, measured without a train step.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _mesh_and_shardings(n, axis="x", devices=None):
    # topology-aware ordering (ICI neighbor rings) via the shared constructor,
    # so the reported bandwidth matches what the strategies' meshes see
    from ddlbench_tpu.distributed import make_mesh

    return make_mesh([(axis, n)], devices=devices)


def _make_collective(name: str, mesh, n: int, buckets: int = 1):
    """Return (fn(local_array) -> local_array, payload_scale) shard_map'd over
    the mesh. payload_scale converts the per-device shard bytes into the
    bytes each device actually moves for the algorithmic-bandwidth figure.

    ``buckets`` splits the local buffer into that many contiguous chunks and
    issues one collective PER CHUNK inside the same program — the wire-level
    shape of the dp engine's ``--comm-buckets`` bucketed reduce-scatter /
    all-gather, measurable here independently of any train step (total
    payload unchanged; what moves is dispatch overhead vs pipelining)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ddlbench_tpu.parallel.gpipe import _shard_map as shard_map

    axis = mesh.axis_names[0]

    if name == "psum":
        def one(x):
            return lax.psum(x, axis)
        # ring allreduce moves 2*(n-1)/n of the buffer per device
        scale = 2.0 * (n - 1) / n
        in_spec, out_spec = P(axis), P(axis)
    elif name == "all_gather":
        def one(x):
            return lax.all_gather(x, axis, tiled=True)
        # each device receives the other n-1 shards
        scale = float(n - 1)
        # out kept "varying" (concatenated globally) so the VMA checker is
        # happy on every shard_map version; the timing is unaffected
        in_spec, out_spec = P(axis), P(axis)
    elif name == "reduce_scatter":
        def one(x):
            return lax.psum_scatter(x, axis, tiled=True)
        # ring RS: each device ships (n-1)/n of the buffer once
        scale = (n - 1) / n
        in_spec, out_spec = P(axis), P(axis)
    elif name == "ppermute":
        def one(x):
            return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])
        scale = 1.0
        in_spec, out_spec = P(axis), P(axis)
    elif name == "all_to_all":
        def one(x):
            return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        scale = (n - 1) / n
        in_spec, out_spec = P(axis), P(axis)
    else:
        raise ValueError(f"unknown collective {name!r}")

    if buckets <= 1:
        op = one
    else:
        def op(x):
            # contiguous equal chunks, one collective each — each chunk's
            # collective is independent dataflow, exactly like the engine's
            # per-bucket psum_scatter
            chunk = x.shape[0] // buckets
            outs = [one(x[b * chunk:(b + 1) * chunk])
                    for b in range(buckets)]
            return jnp.concatenate(outs)

    fn = shard_map(op, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return fn, scale, in_spec


def bench_collective(name: str, mesh, n: int, size_floats: int,
                     iters: int = 10, buckets: int = 1):
    """Time one collective at the given GLOBAL element count; returns a dict.

    ``buckets`` > 1 measures the bucketed variant: same payload, one
    collective per contiguous chunk (see _make_collective)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if buckets < 1:
        raise ValueError(f"buckets must be >= 1 (got {buckets})")
    fn, scale, in_spec = _make_collective(name, mesh, n, buckets)
    # round the per-device shard up to a multiple of n*buckets, so each
    # bucket chunk still splits n ways (all_to_all / reduce_scatter)
    per_dev = max(1, (size_floats + n - 1) // n)
    align = n * buckets
    per_dev = ((per_dev + align - 1) // align) * align
    global_n = per_dev * n
    x = jax.device_put(
        jax.numpy.ones((global_n,), jax.numpy.float32),
        NamedSharding(mesh, in_spec),
    )

    def chained(x0):
        def step(c, _):
            # fold the output into the carry — the dependency defeats
            # dispatch caching. all_gather's output is the concatenation of
            # every shard (n x larger; slice back), reduce_scatter's is a
            # 1/n slice (tile back up) — jnp.resize covers both while
            # keeping the data dependency.
            out = fn(c)
            if out.shape != c.shape:
                out = jnp.resize(out, c.shape)
            return c + 0.0 * out, None
        return lax.scan(step, x0, None, length=iters)[0]

    run = jax.jit(chained)
    jax.block_until_ready(run(x))  # compile
    t0 = time.perf_counter()
    jax.block_until_ready(run(x))
    dt = (time.perf_counter() - t0) / iters

    shard_bytes = per_dev * 4
    moved = shard_bytes * scale
    return {
        "collective": name,
        "global_floats": global_n,
        "shard_bytes": shard_bytes,
        "buckets": buckets,
        "sec_per_op": dt,
        "algbw_gbps": moved / dt / 1e9,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="commbench", description=__doc__)
    p.add_argument("-g", "--devices", type=int, default=None)
    p.add_argument("--collectives",
                   default="psum,all_gather,ppermute,all_to_all",
                   help="also available: reduce_scatter (the dp ZeRO-1 "
                        "gradient collective)")
    p.add_argument("--sizes", default="1e4,1e5,1e6,1e7,1e8",
                   help="global float32 counts (reference sweep: 10..1e8)")
    p.add_argument("--buckets", default="1",
                   help="comma sweep of bucket counts: each point issues "
                        "one collective per contiguous chunk (the dp "
                        "--comm-buckets wire pattern) — e.g. 1,4,8")
    p.add_argument("--iters", type=int, default=10)
    from ddlbench_tpu.distributed import add_platform_arg

    add_platform_arg(p)
    args = p.parse_args(argv)

    import jax

    from ddlbench_tpu.distributed import apply_platform, force_host_mesh_platform

    if args.platform:
        apply_platform(args.platform)
    else:
        force_host_mesh_platform()

    n = args.devices or len(jax.devices())
    mesh = _mesh_and_shardings(n)
    bucket_counts = [int(b) for b in args.buckets.split(",")]
    for name in args.collectives.split(","):
        for size in args.sizes.split(","):
            for buckets in bucket_counts:
                r = bench_collective(name.strip(), mesh, n,
                                     int(float(size)), args.iters,
                                     buckets=buckets)
                print(json.dumps(r), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
