#!/usr/bin/env python
"""Chaos benchmark: kill/preempt/restart supervision + recovery measurement.

The reference suite cannot answer "what happens when a worker dies?" — its
only failure handling is a 2-hour process-group timeout and a pkill script
(SURVEY.md §5.3). This tool makes recovery a *benchmark dimension*: it runs
the train CLI as a child process under a supervisor that

1. schedules ``--kills N`` deterministic SIGKILL injections and
   ``--preempts N`` graceful SIGTERM preemptions (``--inject kill@E:S`` /
   ``preempt@E:S``, one per attempt, spread evenly over the run's global
   steps), plus explicit ``--reshape shrink@E:S:M`` / ``grow@E:S:M``
   world RESHAPES: the child is gracefully preempted at (E, S) (``--inject
   shrink@E:S`` — a checkpoint carrying the logical world-shape metadata
   commits) and every later attempt runs at ``--devices M`` with the
   per-device batch rescaled so the GLOBAL batch is preserved and
   ``--elastic-resume`` reshards the ZeRO-1 flat state (train/reshard.py),
2. relaunches the child with ``--resume`` after every death, with
   exponential backoff and a bounded restart budget (a crash-looping run
   must not spin forever; an exhausted budget exits nonzero),
3. verifies the interrupted trajectory against an uninterrupted baseline
   run **bit-for-bit** (per-step train losses via ``--log-interval 1``
   JSONL records and per-epoch validation loss/accuracy — synthetic data is
   (epoch, step)-addressed, so any divergence means state was lost), and
4. emits a bench.py-style JSON line: recoveries, MTTR (child death -> the
   resumed child's "resumed from" line) split between SIGKILL deaths,
   graceful preemptions (exit code guard/preempt.py PREEMPT_EXIT_CODE with
   a committed checkpoint — counted separately from hard crashes), and
   world reshapes (``mttr_reshape_s`` — death at world N to resumed at
   world M, the reshape recovery time), steps lost per kill, checkpoint
   write overhead (telemetry spans from each attempt's ``--trace``),
   post-reshape trajectory divergence (max |loss delta| vs the baseline
   over the records at/after the first reshape — 0.0 for f32 elastic
   runs), and the stability-guard event counts scraped from the
   children's ``guard:`` lines (anomalies detected / steps skipped /
   rewinds / loss-scale backoffs).

Elastic example (dp ZeRO-1, shrink 4 -> 2 mid-run)::

    python -m ddlbench_tpu.tools.chaosbench --kills 0 \
        --reshape shrink@2:1:2 --platform cpu -b mnist -m lenet \
        -f dp -g 4 --batch-size 2 --steps-per-epoch 4 -e 2 \
        --checkpoint-every-steps 2 -- --dp-shard-update --elastic-slices 4

   The baseline runs uninterrupted at world 4; trajectory_match pins the
   reshaped run's per-step losses to it bitwise (--elastic-slices is what
   makes the f32 reduction order world-invariant — parallel/dp.py).

Usage (CPU smoke)::

    python -m ddlbench_tpu.tools.chaosbench --kills 2 --preempts 1 \
        --platform cpu -b mnist -m lenet --steps-per-epoch 6 -e 2 \
        --batch-size 8 --checkpoint-every-steps 2 --json chaos.json

Any flags after ``--`` are passed through to the train CLI verbatim (e.g.
``-- --anomaly-policy skip --inject nan-grad@1:3`` for an anomaly mix).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from ddlbench_tpu.guard.preempt import PREEMPT_EXIT_CODE


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="chaosbench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--kills", type=int, default=1,
                   help="number of SIGKILL injections to schedule")
    p.add_argument("--preempts", type=int, default=0,
                   help="number of graceful SIGTERM preemptions to "
                        "schedule (interleaved with the kills; the child "
                        "commits a checkpoint and exits with the distinct "
                        "graceful code)")
    p.add_argument("--reshape", action="append", default=[],
                   metavar="KIND@E:S:M",
                   help="elastic world reshape (repeatable): shrink@E:S:M "
                        "or grow@E:S:M gracefully preempts the child at "
                        "epoch E step S and restarts it (and every later "
                        "attempt) at --devices M with --elastic-resume, "
                        "per-device batch rescaled so the global batch is "
                        "preserved (requires -f dp; pass --dp-shard-update "
                        "--elastic-slices E after -- for the bitwise "
                        "trajectory pin)")
    p.add_argument("--restart-budget", type=int, default=None,
                   help="max child relaunches (default: kills + preempts "
                        "+ reshapes + 3)")
    p.add_argument("--backoff-base-s", type=float, default=0.5,
                   help="restart backoff base (doubles per consecutive "
                        "restart, capped by --backoff-max-s)")
    p.add_argument("--backoff-max-s", type=float, default=8.0)
    p.add_argument("-b", "--benchmark", default="mnist")
    p.add_argument("-m", "--model", default="lenet")
    p.add_argument("-f", "--framework", default="single")
    p.add_argument("-g", "--devices", type=int, default=1)
    p.add_argument("-e", "--epochs", type=int, default=2)
    p.add_argument("--steps-per-epoch", type=int, default=6,
                   help="fixed steps/epoch (required: the kill schedule and "
                        "steps-lost accounting are computed from it)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--log-interval", type=int, default=1,
                   help="1 = per-step loss records (the bitwise trajectory "
                        "check compares every overlapping step)")
    p.add_argument("--dtype", default="float32",
                   help="float32 default: the bitwise check is the point")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--checkpoint-every-steps", type=int, default=2)
    p.add_argument("--keep-checkpoints", type=int, default=None)
    p.add_argument("--platform", default=None,
                   help="forwarded to the train CLI (e.g. cpu)")
    p.add_argument("--workdir", default=None,
                   help="scratch dir for checkpoints/logs (default: a "
                        "fresh chaosbench_runs/<pid> dir, removed unless "
                        "--keep-workdir)")
    p.add_argument("--keep-workdir", action="store_true")
    p.add_argument("--json", default=None, help="also write the report here")
    p.add_argument("--skip-verify", action="store_true",
                   help="skip the uninterrupted baseline run (no bitwise "
                        "trajectory check, no overhead denominator A/B)")
    p.add_argument("train_args", nargs="*", default=[],
                   help="extra flags after -- forwarded to the train CLI")
    return p.parse_args(argv)


def kill_schedule(kills: int, epochs: int, steps_per_epoch: int
                  ) -> List[Tuple[int, int]]:
    """Evenly spaced (epoch, step) kill points over the run's global steps.

    Deterministic by construction (no RNG): chaos runs are reproducible
    benchmark configurations, not fuzzing.
    """
    total = epochs * steps_per_epoch
    points = []
    for k in range(1, kills + 1):
        g = max(1, min(total - 1, round(k * total / (kills + 1))))
        points.append((g // steps_per_epoch + 1, g % steps_per_epoch))
    # collapse duplicates from tiny runs while preserving order
    seen, out = set(), []
    for pt in points:
        if pt not in seen:
            seen.add(pt)
            out.append(pt)
    return out


def event_schedule(kills: int, preempts: int, epochs: int,
                   steps_per_epoch: int) -> List[Tuple[str, int, int]]:
    """Deterministic (kind, epoch, step) schedule: kills and graceful
    preemptions interleaved over the evenly-spaced disruption points."""
    points = kill_schedule(kills + preempts, epochs, steps_per_epoch)
    events, k_left, p_left, want_kill = [], kills, preempts, True
    for e, s in points:
        pick_kill = (want_kill and k_left > 0) or p_left <= 0
        if pick_kill:
            events.append(("kill", e, s))
            k_left -= 1
        else:
            events.append(("preempt", e, s))
            p_left -= 1
        want_kill = not want_kill
    return events


def _global_step(epoch: int, step: int, steps_per_epoch: int) -> int:
    return (epoch - 1) * steps_per_epoch + step


def parse_reshapes(specs: List[str]) -> List[Tuple[str, int, int, int]]:
    """``shrink@E:S:M`` / ``grow@E:S:M`` -> (kind, epoch, step, devices)."""
    out = []
    for raw in specs:
        try:
            kind, rest = raw.split("@", 1)
            e_s, s_s, m_s = rest.split(":")
            e, s, m = int(e_s), int(s_s), int(m_s)
        except ValueError:
            raise ValueError(
                f"bad --reshape spec {raw!r}: expected shrink@E:S:M or "
                f"grow@E:S:M (e.g. shrink@2:1:2)")
        if kind not in ("shrink", "grow"):
            raise ValueError(
                f"--reshape kind must be shrink or grow, got {kind!r}")
        if e < 1 or s < 0 or m < 1:
            raise ValueError(f"--reshape {raw!r}: E >= 1, S >= 0, M >= 1")
        out.append((kind, e, s, m))
    return out


def merge_schedule(events: List[Tuple[str, int, int]],
                   reshapes: List[Tuple[str, int, int, int]],
                   steps_per_epoch: int) -> List[Tuple]:
    """One pending list, ordered by global step (kills/preempts keep their
    relative order; a reshape at the same boundary as a kill would race
    the SIGKILL against the SIGTERM, so duplicates are rejected)."""
    merged = list(events) + list(reshapes)
    merged.sort(key=lambda t: _global_step(t[1], t[2], steps_per_epoch))
    seen = set()
    for t in merged:
        pt = (t[1], t[2])
        if pt in seen:
            raise ValueError(
                f"disruption schedule collision at epoch {t[1]} step "
                f"{t[2]}: move the --reshape point off the kill/preempt "
                f"grid")
        seen.add(pt)
    return merged


# Stability-guard event lines (train/loop.py + guard/policy.py print these
# with stable prefixes precisely so the supervisor can aggregate them).
_GUARD_COUNTED = {
    "steps_skipped": re.compile(r"guard: dropped (\d+) non-finite"),
    "loss_scale_backoffs": re.compile(r"guard: loss-scale backoff x(\d+)"),
    "warned_steps": re.compile(
        r"guard: WARNING non-finite gradients \((\d+) step"),
}
_GUARD_FLAGGED = {
    "spikes": re.compile(r"guard: grad-norm spike"),
    "rewinds": re.compile(r"guard: rewinding to the last valid checkpoint"),
}


def guard_events(lines: List[str]) -> Dict[str, int]:
    """Aggregate guard event counts from one attempt's output lines."""
    out = {k: 0 for k in (*_GUARD_COUNTED, *_GUARD_FLAGGED)}
    for line in lines:
        for key, pat in _GUARD_COUNTED.items():
            m = pat.search(line)
            if m:
                out[key] += int(m.group(1))
        for key, pat in _GUARD_FLAGGED.items():
            if pat.search(line):
                out[key] += 1
    out["anomalies_detected"] = sum(
        out[k] for k in ("steps_skipped", "loss_scale_backoffs", "spikes",
                         "rewinds", "warned_steps"))
    return out


def _train_argv(args, ckpt_dir: Optional[str], jsonl: str,
                trace: Optional[str], inject: List[str],
                resume: bool, devices: Optional[int] = None,
                batch_size: Optional[int] = None,
                elastic: bool = False) -> List[str]:
    argv = [sys.executable, "-m", "ddlbench_tpu.cli",
            "-b", args.benchmark, "-m", args.model, "-f", args.framework,
            "-g", str(devices if devices is not None else args.devices),
            "-e", str(args.epochs),
            "--steps-per-epoch", str(args.steps_per_epoch),
            "--batch-size",
            str(batch_size if batch_size is not None else args.batch_size),
            "--log-interval", str(args.log_interval),
            "--dtype", args.dtype, "--seed", str(args.seed),
            "--jsonl", jsonl]
    if elastic:
        argv += ["--elastic-resume"]
    if args.platform:
        argv += ["--platform", args.platform]
    if ckpt_dir:
        argv += ["--checkpoint-dir", ckpt_dir,
                 "--checkpoint-every-steps", str(args.checkpoint_every_steps)]
        if args.keep_checkpoints:
            argv += ["--keep-checkpoints", str(args.keep_checkpoints)]
    if resume:
        argv += ["--resume"]
    if trace:
        argv += ["--trace", trace]
    for spec in inject:
        argv += ["--inject", spec]
    argv += list(args.train_args)
    return argv


class AttemptResult:
    def __init__(self):
        self.rc: Optional[int] = None
        self.wall_s = 0.0
        self.resumed_line: Optional[str] = None
        self.resumed_at: Optional[float] = None  # monotonic
        self.died_at: Optional[float] = None
        self.lines: List[str] = []


def _run_attempt(argv: List[str], log_path: str) -> AttemptResult:
    """Launch one child; stream stdout (timestamping the recovery line)."""
    res = AttemptResult()
    t0 = time.monotonic()
    with open(log_path, "w") as log:
        proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        assert proc.stdout is not None
        for line in proc.stdout:
            log.write(line)
            res.lines.append(line.rstrip("\n"))
            if line.startswith("resumed from") and res.resumed_at is None:
                res.resumed_at = time.monotonic()
                res.resumed_line = line.strip()
        res.rc = proc.wait()
    res.died_at = time.monotonic()
    res.wall_s = res.died_at - t0
    return res


def _parse_resumed_global(line: Optional[str], steps_per_epoch: int
                          ) -> Optional[int]:
    """'resumed from <dir> epoch E[ step S (mid-epoch)]' -> resumed global step."""
    if not line:
        return None
    toks = line.split()
    try:
        ep = int(toks[toks.index("epoch") + 1])
        if "step" in toks:
            return _global_step(ep, int(toks[toks.index("step") + 1]) + 1,
                                steps_per_epoch)
        return ep * steps_per_epoch
    except (ValueError, IndexError):
        return None


def _span_seconds(trace_path: str, names: Tuple[str, ...]) -> Dict[str, float]:
    """Total duration (s) of the named complete-spans in a Chrome trace."""
    totals = {n: 0.0 for n in names}
    try:
        with open(trace_path) as f:
            events = json.load(f).get("traceEvents", [])
    except (OSError, ValueError):
        return totals
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") in totals:
            totals[ev["name"]] += ev.get("dur", 0) / 1e6
    return totals


def _jsonl_trajectory(path: str) -> Tuple[Dict, Dict]:
    """(train, valid) maps from a metrics JSONL; last write wins, so a
    chaos run's re-executed steps are compared at their FINAL values."""
    train: Dict[Tuple[int, float], float] = {}
    valid: Dict[int, Tuple[float, float]] = {}
    try:
        with open(path) as f:
            for raw in f:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    continue
                if rec.get("kind") == "train_interval":
                    train[(rec["epoch"], rec["progress_pct"])] = rec["loss"]
                elif rec.get("kind") == "valid":
                    valid[rec["epoch"]] = (rec["loss"], rec["accuracy"])
    except OSError:
        pass
    return train, valid


def verify_trajectory(baseline_jsonl: str, chaos_jsonl: str
                      ) -> Tuple[bool, List[str]]:
    """Bit-for-bit comparison (exact float equality — no tolerance: the
    commit protocol's claim is bitwise resume, not approximate resume)."""
    return _verify_maps(_jsonl_trajectory(baseline_jsonl),
                        _jsonl_trajectory(chaos_jsonl))


def _verify_maps(baseline: Tuple[Dict, Dict], chaos: Tuple[Dict, Dict]
                 ) -> Tuple[bool, List[str]]:
    b_train, b_valid = baseline
    c_train, c_valid = chaos
    mismatches = []
    for key, loss in sorted(b_train.items()):
        if key not in c_train:
            mismatches.append(f"missing train record {key}")
        elif c_train[key] != loss:
            mismatches.append(
                f"train loss @ {key}: {c_train[key]!r} != {loss!r}")
    for ep, lv in sorted(b_valid.items()):
        if ep not in c_valid:
            mismatches.append(f"missing valid record epoch {ep}")
        elif c_valid[ep] != lv:
            mismatches.append(
                f"valid @ epoch {ep}: {c_valid[ep]!r} != {lv!r}")
    return not mismatches, mismatches


def run_chaos(args) -> Dict[str, Any]:
    # absolute: orbax rejects relative checkpoint paths at RESTORE time,
    # which otherwise burns the whole restart budget on the default workdir
    workdir = os.path.abspath(
        args.workdir or os.path.join("chaosbench_runs", str(os.getpid())))
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ckpt")
    reshapes = parse_reshapes(getattr(args, "reshape", []))
    if reshapes and args.framework != "dp":
        raise ValueError(
            "--reshape changes the dp world size; run it with -f dp "
            "(--dp-shard-update after -- for the ZeRO-1 reshard path)")
    # the GLOBAL batch is the invariant across a reshape: the data stream
    # is (epoch, step)-addressed at that batch, so the per-device batch
    # rescales with each new world
    global_batch = args.batch_size * args.devices
    elastic_slices = None
    if "--elastic-slices" in args.train_args:
        # the child's elastic gates must hold at EVERY scheduled world, or
        # each post-reshape relaunch dies in RunConfig.validate and the
        # supervisor burns the whole restart budget on a usage error
        elastic_slices = int(args.train_args[
            args.train_args.index("--elastic-slices") + 1])
    for kind, e, s, m in reshapes:
        if global_batch % m:
            raise ValueError(
                f"--reshape {kind}@{e}:{s}:{m}: global batch "
                f"{global_batch} must divide by the new device count {m}")
        if elastic_slices is not None and \
                (m & (m - 1) or elastic_slices % m):
            raise ValueError(
                f"--reshape {kind}@{e}:{s}:{m}: the child's "
                f"--elastic-slices {elastic_slices} needs a power-of-two "
                f"device count dividing it; {m} fails that gate")
    schedule = merge_schedule(
        event_schedule(args.kills, getattr(args, "preempts", 0),
                       args.epochs, args.steps_per_epoch),
        reshapes, args.steps_per_epoch)
    budget = (args.restart_budget if args.restart_budget is not None
              else len(schedule) + 3)

    # actual backend record (shared classification + loud cpu-fallback
    # warning — distributed.record_provenance); the children run the
    # compute but on the same machine, so the supervisor's backend is
    # the fleet's backend
    from ddlbench_tpu.distributed import record_provenance

    prov = record_provenance(args.platform, "chaosbench")
    report: Dict[str, Any] = {
        **prov,
        "metric": "chaosbench_recovery",
        "benchmark": args.benchmark, "arch": args.model,
        "framework": args.framework,
        "epochs": args.epochs, "steps_per_epoch": args.steps_per_epoch,
        "checkpoint_every_steps": args.checkpoint_every_steps,
        "kills_scheduled": [f"{t[0]}@{t[1]}:{t[2]}" for t in schedule
                            if t[0] == "kill"],
        "preempts_scheduled": [f"{t[0]}@{t[1]}:{t[2]}" for t in schedule
                               if t[0] == "preempt"],
        "reshapes_scheduled": [f"{k}@{e}:{s}:{m}"
                               for k, e, s, m in reshapes],
        "restart_budget": budget,
    }

    # -- baseline: uninterrupted, checkpoint-free (overhead denominator +
    # -- the bitwise trajectory reference) ---------------------------------
    baseline_jsonl = os.path.join(workdir, "baseline.jsonl")
    if not args.skip_verify:
        print(f"chaosbench: baseline run (uninterrupted, no checkpoints)",
              flush=True)
        base = _run_attempt(
            _train_argv(args, None, baseline_jsonl, None, [], resume=False),
            os.path.join(workdir, "baseline.log"))
        if base.rc != 0:
            report["error"] = f"baseline run failed (rc={base.rc})"
            print(json.dumps(report), flush=True)
            return report
        report["baseline_wall_s"] = round(base.wall_s, 3)

    # -- chaos run: supervised kill/preempt/restart loop -------------------
    chaos_jsonl = os.path.join(workdir, "chaos.jsonl")
    pending = list(schedule)
    attempts: List[AttemptResult] = []
    mttr_s: List[float] = []  # hard-kill MTTRs (legacy field name)
    mttr_preempt_s: List[float] = []  # graceful-preemption MTTRs
    mttr_reshape_s: List[float] = []  # world-reshape recovery times
    steps_lost: List[int] = []
    recoveries = restarts = 0
    kills_fired = preempts_fired = reshapes_fired = graceful_exits = 0
    consecutive_failures = 0
    save_s = restore_s = 0.0
    last_death: Optional[float] = None
    death_kind: Optional[str] = None
    killed_at: Optional[Tuple[int, int]] = None
    guard_totals: Dict[str, int] = {}
    completed = False
    cur_devices, cur_batch = args.devices, args.batch_size
    elastic = bool(reshapes)  # harmless on non-reshaped attempts

    while True:
        attempt_no = len(attempts)
        inject = [f"{pt[0]}@{pt[1]}:{pt[2]}" for pt in pending[:1]]
        trace = os.path.join(workdir, f"attempt_{attempt_no}.trace.json")
        argv = _train_argv(args, ckpt_dir, chaos_jsonl, trace, inject,
                           resume=True, devices=cur_devices,
                           batch_size=cur_batch, elastic=elastic)
        print(f"chaosbench: attempt {attempt_no} (devices {cur_devices})"
              + (f" (pending {inject[0]})" if inject
                 else " (no more disruptions)"),
              flush=True)
        res = _run_attempt(argv,
                           os.path.join(workdir, f"attempt_{attempt_no}.log"))
        attempts.append(res)
        spans = _span_seconds(trace, ("checkpoint_save",
                                      "checkpoint_restore"))
        save_s += spans["checkpoint_save"]
        restore_s += spans["checkpoint_restore"]
        for key, v in guard_events(res.lines).items():
            guard_totals[key] = guard_totals.get(key, 0) + v

        if res.resumed_at is not None and last_death is not None:
            mttr = res.resumed_at - last_death
            (mttr_preempt_s if death_kind == "preempt"
             else mttr_reshape_s if death_kind == "reshape"
             else mttr_s).append(mttr)
            recoveries += 1
            resumed_g = _parse_resumed_global(res.resumed_line,
                                              args.steps_per_epoch)
            if resumed_g is not None and killed_at is not None and \
                    steps_lost and steps_lost[-1] is None:
                steps_lost[-1] = _global_step(*killed_at,
                                              args.steps_per_epoch) - resumed_g
            last_death, death_kind = None, None

        if res.rc == 0:
            completed = True
            break
        if res.rc == -signal.SIGKILL and pending and \
                pending[0][0] == "kill" and \
                any(l.startswith("fault-inject: kill") for l in res.lines):
            killed_at = pending.pop(0)[1:]
            kills_fired += 1
            steps_lost.append(None)  # filled in by the next resume line
            last_death, death_kind = res.died_at, "kill"
            consecutive_failures = 0
        elif res.rc == PREEMPT_EXIT_CODE and \
                any(l.startswith("preempt: checkpoint committed")
                    for l in res.lines):
            # graceful exit: the child committed its preemption checkpoint
            # and exited with the distinct code — an EXPECTED eviction, not
            # a crash (counted, timed, and budgeted separately)
            # pop the scheduled spec only when the INJECTED preemption
            # actually fired (kill-branch parity): a stray external SIGTERM
            # also exits 75 with a committed line, but must not consume the
            # scheduled disruption point
            if pending and pending[0][0] in ("shrink", "grow") and \
                    any(l.startswith(f"fault-inject: {pending[0][0]}")
                        for l in res.lines):
                # world RESHAPE: the child committed its logical-metadata
                # checkpoint; every attempt from here runs at the new
                # world, per-device batch rescaled so the global batch —
                # the (epoch, step) data-addressing invariant — holds
                kind, e, s, m = pending.pop(0)
                reshapes_fired += 1
                cur_devices, cur_batch = m, global_batch // m
                print(f"chaosbench: reshape {kind}@{e}:{s} -> devices "
                      f"{m} (batch {cur_batch}/device, elastic resume)",
                      flush=True)
                last_death, death_kind = res.died_at, "reshape"
            else:
                if pending and pending[0][0] == "preempt" and \
                        any(l.startswith("fault-inject: preempt")
                            for l in res.lines):
                    pending.pop(0)
                    preempts_fired += 1
                last_death, death_kind = res.died_at, "preempt"
            graceful_exits += 1
            consecutive_failures = 0
        else:
            consecutive_failures += 1
            print(f"chaosbench: unexpected child exit rc={res.rc}",
                  flush=True)
        restarts += 1
        if restarts > budget:
            report["error"] = (f"restart budget ({budget}) exhausted after "
                               f"{len(attempts)} attempts")
            break
        delay = min(args.backoff_max_s,
                    args.backoff_base_s * 2 ** consecutive_failures)
        print(f"chaosbench: restarting in {delay:.2f}s", flush=True)
        time.sleep(delay)

    chaos_wall = sum(a.wall_s for a in attempts)
    report.update({
        "completed": completed,
        "attempts": len(attempts),
        "restarts": restarts,
        # fired counts, not args.kills: tiny runs collapse duplicate
        # disruption points, and the report must agree with mttr/steps_lost
        "kills": kills_fired,
        "preempts": preempts_fired,
        "reshapes": reshapes_fired,
        "final_devices": cur_devices,
        "graceful_exits": graceful_exits,
        "recoveries": recoveries,
        "mttr_s": [round(t, 3) for t in mttr_s],
        "mttr_s_mean": round(sum(mttr_s) / len(mttr_s), 3) if mttr_s else None,
        "mttr_preempt_s": [round(t, 3) for t in mttr_preempt_s],
        "mttr_preempt_s_mean": (round(sum(mttr_preempt_s)
                                      / len(mttr_preempt_s), 3)
                                if mttr_preempt_s else None),
        "mttr_reshape_s": [round(t, 3) for t in mttr_reshape_s],
        "mttr_reshape_s_mean": (round(sum(mttr_reshape_s)
                                      / len(mttr_reshape_s), 3)
                                if mttr_reshape_s else None),
        "steps_lost_per_kill": steps_lost,
        "guard": guard_totals,
        "chaos_wall_s": round(chaos_wall, 3),
        "checkpoint_save_s": round(save_s, 3),
        "checkpoint_restore_s": round(restore_s, 3),
        "checkpoint_overhead_pct": (
            round(100.0 * save_s / chaos_wall, 2) if chaos_wall else None),
    })

    if not args.skip_verify and completed:
        b_train, b_valid = _jsonl_trajectory(baseline_jsonl)
        c_train, c_valid = _jsonl_trajectory(chaos_jsonl)
        match, mismatches = _verify_maps((b_train, b_valid),
                                         (c_train, c_valid))
        report["trajectory_match"] = match
        if not match:
            report["trajectory_mismatches"] = mismatches[:20]
        if reshapes:
            # post-reshape trajectory divergence: max |loss delta| vs the
            # baseline over records at/after the FIRST reshape point —
            # 0.0 for f32 elastic runs (the headline reshape number next
            # to mttr_reshape_s; nonzero quantifies drift when a run
            # reshapes without --elastic-slices)
            spe = args.steps_per_epoch
            e0, s0 = reshapes[0][1], reshapes[0][2]
            g0 = _global_step(e0, s0, spe)
            div = 0.0
            for (ep, prog), loss in b_train.items():
                g = (ep - 1) * spe + round(prog * spe / 100.0) - 1
                if g >= g0 and (ep, prog) in c_train:
                    div = max(div, abs(c_train[(ep, prog)] - loss))
            for ep, (l, _a) in b_valid.items():
                if ep >= e0 and ep in c_valid:
                    div = max(div, abs(c_valid[ep][0] - l))
            report["post_reshape_divergence"] = div

    print(json.dumps(report), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    if not args.keep_workdir and args.workdir is None:
        shutil.rmtree(workdir, ignore_errors=True)
    return report


def main(argv=None) -> int:
    args = _parse_args(argv)
    try:
        report = run_chaos(args)
    except ValueError as e:
        # schedule-construction errors (--reshape grammar, batch/world
        # divisibility, kill-point collisions) are usage errors, not bugs
        print(f"chaosbench: {e}", file=sys.stderr, flush=True)
        return 2
    # nonzero whenever no run COMPLETED (e.g. the restart budget was
    # exhausted on a crash-looping child), an error was recorded, or the
    # recovered trajectory diverged — supervisor callers key off this
    ok = bool(report.get("completed")) and "error" not in report and \
        report.get("trajectory_match", True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
