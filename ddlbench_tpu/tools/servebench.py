"""Serving benchmark: continuous batching vs static batching under load.

Drives the continuous-batching engine (serve/engine.py) with a seeded
open- or closed-loop workload (serve/workload.py) and reports the serving
metrics that matter for "heavy traffic from millions of users": TTFT and
inter-token-latency p50/p95/p99 plus **goodput under SLO** — output tokens
per time unit counting only requests whose TTFT and mean ITL met their
SLOs (telemetry/stats.serve_summary). One JSON line per configuration,
like every other tool:

    {"tool": "servebench", "policy": "continuous", "arrival": "poisson",
     "goodput_tokens_per_unit": G, "ttft_p95": T, ...}

Time is VIRTUAL by default: one unit = one model pass (a [max_batch, 1]
decode step or one prefill chunk — the engine's cost model, under which
batch parallelism is free and wasted passes are what scheduling policies
differ on). That makes every reported number bitwise-reproducible under a
fixed seed — the same repro discipline as every other tool — while
``--wall-clock`` adds real elapsed seconds for on-chip runs.

The default sweep runs each requested policy (continuous, then the
static whole-batch baseline) over the SAME workload at the SAME pool
size, so the goodput delta is pure scheduling effect.

Usage:
    python -m ddlbench_tpu.tools.servebench [-m transformer_s]
        [-b synthtext] [--arrival poisson|bursty|closed] [--rate 0.5]
        [--requests 64] [--max-batch 8] [--pool-pages 64] [--page 16]
        [--max-len 256] [--slo-ttft 16] [--slo-itl 2.0]
        [--shared-prefix 4:64] [--prefix-cache]
        [--sample temperature:0.8,top-k:40] [--kv-dtype int8]
        [--speculative ngram:3:4] [--deadline-slack 64] [--retry 2:8]
        [--tier-mix 0.5] [--heartbeat 16] [--platform cpu]

Deadlines + SLO tiers (ISSUE 15): ``--deadline-slack S`` stamps every
request with a completion deadline (arrival + S) — hopeless requests are
SHED at admission (the driver retries with bounded backoff under
``--retry N:B``, then rejects) and expired ones cancel into the named
``timeout`` terminal state; ``--tier-mix F`` draws that fraction into
the preemptible ``batch`` tier (interactive admits ahead, batch evicts
first) with the per-tier TTFT/ITL/goodput split in the row. All the new
counters are flag-gated; plain rows keep the pinned schema.
tools/servechaos.py composes the same load with replica kill/stall
injection.

Raw-speed levers (ISSUE 13): ``--kv-dtype`` stores the shared KV pool in
bf16 (half the f32 bytes) or int8 (a quarter — quantize-at-write with
per-page scales, dequant fused in-kernel; the row's ``pool_bytes`` makes
the capacity claim a number), and ``--speculative ngram:N:K`` turns the
decode step into a drafted verify pass (token streams bitwise identical
to greedy; ``spec_accept_rate``/``tokens_per_pass`` report whether the
traffic's self-similarity paid for it).

Self-healing autoscaler (ISSUE 19): ``--autoscale LO:HI`` puts a
FleetController (serve/autoscaler.py) in the loop — per-window SLO
attainment/goodput + shed/timeout/queue signals drive live ``resize()``
within [LO, HI] clamps (hysteresis, per-direction cooldowns, bounded
actuation budget), and a dead or heartbeat-drained replica is
auto-repaired through the factory spawn. ``--shape diurnal|ramp|spike``
grows the matching traffic curves (prompts bitwise-identical across
shapes), so the headline A/B is ``--shape diurnal --autoscale 1:N`` vs a
static ``--replicas N`` fleet: equal goodput, strictly fewer
replica-hours. The tool exits nonzero if an autoscaled run loses a
request.

The prefix-cache A/B: ``--shared-prefix G:P`` synthesizes G groups of
requests sharing a P-token prompt head, and ``--prefix-cache`` lets the
continuous engine serve cached heads from resident KV pages — compare the
``prefill_tokens`` / ``ttft_p50`` / ``prefix_*`` fields against the same
invocation without the flag (identical token streams, pinned).

Observability (PR 11): ``--trace PATH`` records the request-lifecycle
trace (serve/engine.py events in virtual time, one Chrome-trace track per
request per replica plus counter tracks) and writes it Perfetto-loadable
to PATH (``PATH.<policy>`` when several policies run) with the SLOs
embedded in the metadata. Tracing is metrics-neutral: the JSON line and
the token streams are bitwise identical with or without it (pinned).
``--timeline`` additionally reduces the trace in-process
(telemetry/serveview.py) and embeds the per-window SLO/goodput table +
TTFT/ITL component breakdowns in the JSON line (``--window`` sets the
bucket width).
"""

from __future__ import annotations

import argparse
import bisect
import json
import sys
import time


# engine stats keys that only carry signal under --speculative: excluded
# from plain rows so the schema-pinned key set is unchanged when the flag
# is off (the --resize pattern)
_SPEC_FIELDS = frozenset((
    "spec_passes", "spec_drafted", "spec_accepted", "decode_tokens",
    "spec_accept_rate", "tokens_per_pass"))

# engine stats keys that only carry signal under --deadline-slack
# (admission shedding / timeout cancellation): same flag-gating pattern
_CHAOS_FIELDS = frozenset(("shed", "timeouts"))

# stats keys only the disaggregated server emits (handoff wire-byte
# accounting): gated so a plain aggregated row keeps the pinned schema
# even if a future server grows the counters
_DISAGG_FIELDS = frozenset((
    "shipped_requests", "shipped_pages", "shipped_payload_bytes",
    "shipped_sidecar_bytes", "shipped_checksum_bytes"))

# engine stats keys that only carry signal when the SDC checksum ledger
# is armed (--scrub here; --corrupt in servechaos): plain rows stay
# byte-identical in schema — the engine always counts, the row only
# shows the counters when the flag asked for them
_SDC_FIELDS = frozenset((
    "sdc_injected", "sdc_detected", "sdc_quarantined", "sdc_recovered",
    "sdc_scrubbed", "sdc_recompute_checks", "sdc_wire_detected",
    "sdc_wire_repaired"))


def parse_disaggregate(spec, perr):
    """Parse ``--disaggregate P:D`` (prefill:decode replica counts) —
    shared with servechaos. Returns (P, D) or None for an absent spec."""
    if not spec:
        return None
    try:
        p_s, d_s = spec.split(":")
        pd = (int(p_s), int(d_s))
    except ValueError:
        perr(f"--disaggregate wants P:D (prefill:decode replicas), "
             f"got {spec!r}")
    if pd[0] < 1 or pd[1] < 1:
        perr(f"--disaggregate {spec!r}: both fleets need >= 1 replica")
    return pd


def _round6(v):
    """round(_, 6) through nested timeline/breakdown structures so the
    JSON stays bitwise-reproducible and diff-friendly."""
    if isinstance(v, float):
        return round(v, 6)
    if isinstance(v, dict):
        return {k: _round6(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_round6(x) for x in v]
    return v


def parse_retry(spec, perr):
    """Parse a ``--retry N:B`` spec (shared by servebench and servechaos
    so the sibling tools cannot diverge on bounds): N >= 1 resubmissions,
    base backoff B >= 0. Returns (N, B) or None for an absent spec."""
    if not spec:
        return None
    try:
        n_s, b_s = spec.split(":")
        retry = (int(n_s), float(b_s))
    except ValueError:
        perr(f"--retry wants N:B (retries:base_backoff), got {spec!r}")
    if retry[0] < 1 or retry[1] < 0:
        perr(f"--retry {spec!r}: N >= 1 and B >= 0")
    return retry


def parse_autoscale(spec, perr):
    """Parse ``--autoscale LO:HI`` (replica clamps for the closed-loop
    controller) — shared with servechaos. Returns (lo, hi) or None."""
    if not spec:
        return None
    try:
        lo_s, hi_s = spec.split(":")
        lohi = (int(lo_s), int(hi_s))
    except ValueError:
        perr(f"--autoscale wants LO:HI (min:max replicas), got {spec!r}")
    if lohi[0] < 1 or lohi[1] < lohi[0]:
        perr(f"--autoscale {spec!r}: needs 1 <= LO <= HI")
    return lohi


def shed_accounting(requests, completed, shed, timeouts, driver_stats):
    """Terminal-state accounting shared by servebench and servechaos —
    the cross-tool no-loss gate must come from ONE formula: every request
    ends completed, timed out, or rejected; anything else is lost
    (``requests_lost == 0`` is the invariant the chaos gates pin)."""
    retries = driver_stats.get("retries", 0)
    rejected = driver_stats.get("rejected", 0)
    submissions = requests + retries
    return {
        "retries": retries,
        "rejected": rejected,
        "requests_lost": requests - completed - timeouts - rejected,
        # zero-requests guard: the degenerate row stays schema-stable
        # with all-zero rates, never a ZeroDivisionError (the
        # serve_summary contract)
        "shed_rate": (round(shed / submissions, 6) if submissions else 0.0),
        "timeout_rate": (round(timeouts / requests, 6)
                         if requests else 0.0),
        "retry_amplification": (round(submissions / requests, 6)
                                if requests else 1.0),
    }


def _resize_fn(n: int):
    def fire(server, clock):
        rep = server.resize(n, now=clock)
        print(f"servebench: resize @ {clock:g} -> {n} replicas "
              f"(evicted {rep['evicted']}, redistributed "
              f"{rep['redistributed']})", file=sys.stderr, flush=True)
    return fire


def _merge_events(resizes, events):
    """One sorted ``(at, fn(server, clock))`` schedule from the legacy
    ``(at, n)`` resize specs plus arbitrary chaos injections (servechaos
    passes kill/stall closures through ``events``)."""
    ev = [(at, _resize_fn(n)) for at, n in (resizes or [])]
    ev.extend(events or [])
    ev.sort(key=lambda e: e[0])
    return ev


def _fire_events(server, clock: float, events):
    """Fire every due ``(at, fn)`` event (a sorted list the caller
    consumes) — resizes, replica kills, stalls."""
    while events and clock >= events[0][0]:
        at, fn = events.pop(0)
        fn(server, clock)


class _Submitter:
    """Driver-side admission with the bounded retry-with-backoff policy
    (ISSUE 15): a SHED submission (deadline admission control refused the
    request) retries after ``backoff * 2**attempt`` time units, up to
    ``retries`` times, then goes terminal as REJECTED — so shed rate and
    retry amplification become reported numbers instead of silent driver
    behavior. ``stats`` collects ``retries``/``rejected`` for the JSON
    row. With no deadlines in the traffic nothing is ever shed and this
    reduces to plain ``server.submit``."""

    def __init__(self, server, retry=None, deadline_slack=None, stats=None):
        self.server = server
        self.retries, self.backoff = retry if retry else (0, 1.0)
        self.slack = deadline_slack
        self.pending = []  # (due, rid, attempt, req), sorted by due
        self.stats = stats if stats is not None else {}
        self.stats.setdefault("retries", 0)
        self.stats.setdefault("rejected", 0)

    def offer(self, req, clock: float, attempt: int = 0) -> str:
        """One submission attempt -> "ok" | "retry" | "rejected"."""
        if req.arrival is None:
            req.arrival = clock  # closed loop stamps at release
        if self.slack is not None and req.deadline is None:
            # closed-loop deadline stamp: the workload could not know the
            # release time (open-loop requests arrive pre-stamped)
            req.deadline = req.arrival + self.slack
        if self.server.submit(req, now=clock):
            return "ok"
        if attempt < self.retries:
            self.stats["retries"] += 1
            bisect.insort(self.pending,
                          (clock + self.backoff * (2 ** attempt),
                           req.rid, attempt + 1, req))
            return "retry"
        self.stats["rejected"] += 1
        return "rejected"

    def release_due(self, clock: float) -> int:
        """Fire due retries; returns how many went terminal (rejected)."""
        dead = 0
        while self.pending and self.pending[0][0] <= clock:
            _, _, attempt, req = self.pending.pop(0)
            if self.offer(req, clock, attempt) == "rejected":
                dead += 1
        return dead

    def next_due(self):
        return self.pending[0][0] if self.pending else None


def _advance_controllers(controllers, clock: float):
    """Kick every autoscale controller up to the virtual clock — called
    after each global step and idle jump so decisions land at
    deterministic instants (serve/autoscaler.py's driver contract)."""
    for c in controllers or ():
        c.advance(clock)


def run_open_loop(server, reqs, resizes=None, events=None, retry=None,
                  deadline_slack=None, driver_stats=None, controllers=None):
    """Release requests at their arrival times; returns the final clock.
    ``events`` is a list of timed ``(at, fn(server, clock))`` injections
    (resizes are sugar for them); ``retry=(N, backoff)`` arms the shed
    retry policy and ``driver_stats`` (a dict) receives its counters;
    ``controllers`` are autoscale FleetControllers advanced in lockstep
    with the virtual clock (they resize/repair the fleet live)."""
    clock, i = 0.0, 0
    ev = _merge_events(resizes, events)
    sub = _Submitter(server, retry, deadline_slack, driver_stats)
    pend = sorted(reqs, key=lambda r: (r.arrival, r.rid))
    while i < len(pend) or sub.pending or server.has_work():
        _fire_events(server, clock, ev)
        sub.release_due(clock)
        while i < len(pend) and pend[i].arrival <= clock:
            sub.offer(pend[i], clock)
            i += 1
        if not server.has_work():
            # idle: jump to the next arrival, pending retry, or
            # scheduled injection — skipping events here would fire a
            # kill/stall/resize under DIFFERENT load than its schedule
            # asked for (events dated past the end of all work still
            # never fire; the loop exits first, surfaced by the caller)
            nxts = [t for t in (
                pend[i].arrival if i < len(pend) else None,
                sub.next_due(),
                ev[0][0] if ev else None) if t is not None]
            if not nxts:
                break
            clock = max(clock, min(nxts))
            # the controller sees idle time too — that is where the
            # diurnal trough's scale-downs come from
            _advance_controllers(controllers, clock)
            continue
        rep = server.step(clock)
        clock += rep.cost
        _advance_controllers(controllers, clock)
    return clock


def run_closed_loop(server, reqs, concurrency: int, resizes=None,
                    events=None, retry=None, deadline_slack=None,
                    driver_stats=None, controllers=None):
    """Keep ``concurrency`` requests in flight; each TERMINAL event —
    completion, timeout, or a shed request exhausting its retries —
    releases the next. Returns the final clock."""
    clock, nxt, done = 0.0, 0, 0
    ev = _merge_events(resizes, events)
    sub = _Submitter(server, retry, deadline_slack, driver_stats)
    n = len(reqs)
    outstanding = 0  # released and not yet terminal (incl. pending retry)

    def top_up():
        nonlocal nxt, done, outstanding
        while nxt < n and outstanding < concurrency:
            st = sub.offer(reqs[nxt], clock)
            nxt += 1
            if st == "rejected":
                done += 1
            else:
                outstanding += 1

    top_up()
    while done < n:
        _fire_events(server, clock, ev)
        dead = sub.release_due(clock)
        done += dead
        outstanding -= dead
        top_up()
        if not server.has_work():
            # jump to the next retry or scheduled injection (same
            # fire-at-the-scheduled-load contract as the open loop)
            nxts = [t for t in (sub.next_due(),
                                ev[0][0] if ev else None)
                    if t is not None]
            if nxts:
                clock = max(clock, min(nxts))
                _advance_controllers(controllers, clock)
                continue
            if outstanding:
                # a server-INTERNAL shed (failover/drain/resize under
                # deadlines retires a request without any driver-visible
                # completion/timeout) would otherwise hold its
                # concurrency slot forever and strand the rest of the
                # workload — reconcile: the vanished requests are
                # terminal (they surface in requests_lost) and their
                # slots release the tail
                done += outstanding
                outstanding = 0
                top_up()
                continue
            break  # everything released went terminal
        rep = server.step(clock)
        clock += rep.cost
        _advance_controllers(controllers, clock)
        term = len(rep.completed) + len(rep.timed_out)
        done += term
        outstanding -= term
        top_up()
    return clock


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", default="transformer_s")
    p.add_argument("-b", "--benchmark", default="synthtext")
    p.add_argument("--policies", default="continuous,static",
                   help="comma list among continuous,static — each runs "
                        "the same workload at the same pool size")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--pool-pages", type=int, default=64)
    p.add_argument("--page", type=int, default=16)
    p.add_argument("--max-len", type=int, default=256)
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="tokens per prefill call (page multiple; default: "
                        "one page; 0 = whole prompt in one padded call)")
    p.add_argument("--token-budget", type=int, default=0,
                   help="tokens one step may pack (0 = max_batch + 2 "
                        "prefill chunks)")
    p.add_argument("--replicas", type=int, default=1,
                   help="independent data-parallel serving replicas "
                        "(least-loaded dispatch)")
    p.add_argument("--serve-tp", type=int, default=1, metavar="N",
                   help="tensor-parallel width of ONE replica: the serve "
                        "programs shard Megatron-style over a mesh "
                        "'model' axis (N devices per replica share one "
                        "page table), so models larger than one chip's "
                        "HBM serve at all. Default 1 keeps the single-"
                        "chip programs bitwise-unchanged")
    p.add_argument("--disaggregate", default=None, metavar="P:D",
                   help="disaggregated serving: a P-replica PREFILL fleet "
                        "feeds a D-replica DECODE fleet by KV-page "
                        "shipping (serve/handoff.py) — int8 pools ship "
                        "f32/4 payload bytes. Token streams pin bitwise "
                        "vs the aggregated fleet; the row gains "
                        "disaggregate/prefill_replicas/decode_replicas + "
                        "shipped_* fields. Continuous policy only; "
                        "replaces --replicas and excludes --resize")
    p.add_argument("--resize", action="append", default=[], metavar="AT:N",
                   help="live replica resize schedule (repeatable): at "
                        "virtual time AT scale the fleet to N replicas "
                        "under load — scale-down drains replicas (in-"
                        "flight requests evicted onto the recompute path, "
                        "queues redistributed least-loaded), scale-up "
                        "shares the jitted callables. No request is lost "
                        "and token streams stay bitwise vs an un-resized "
                        "control (pinned); the JSON row gains "
                        "resize_events/requests_lost fields")
    p.add_argument("--autoscale", default=None, metavar="LO:HI",
                   help="close the loop: a FleetController "
                        "(serve/autoscaler.py) watches windowed SLO "
                        "attainment/goodput + shed/timeout/queue signals "
                        "and actuates resize() live — scale-up under "
                        "pressure, scale-down in idle troughs, AUTO-REPAIR "
                        "of dead/heartbeat-drained replicas through the "
                        "factory spawn — with the fleet clamped to "
                        "[LO, HI]. The row gains replica_hours/"
                        "scale_events/repairs/autoscale_attainment + the "
                        "decision ledger, and the tool exits nonzero if "
                        "the run loses a request. Excludes --resize "
                        "(the controller owns the schedule); with "
                        "--disaggregate each fleet gets its own "
                        "controller")
    p.add_argument("--scale-window", type=float, default=32.0, metavar="W",
                   help="autoscale observation-window width in time units "
                        "(one decision per window)")
    p.add_argument("--scale-cooldown", type=float, default=64.0,
                   metavar="C",
                   help="min time between same-direction autoscale "
                        "actuations (repair is exempt: restoring capacity "
                        "the policy already chose is not a scale decision)")
    p.add_argument("--arrival", default="poisson",
                   choices=("poisson", "bursty", "closed"))
    p.add_argument("--shape", default=None,
                   choices=("diurnal", "ramp", "spike"),
                   help="traffic shape layered on --arrival poisson: the "
                        "rate curve (daily cycle / linear ramp / flash "
                        "crowd) modulates inter-arrivals drawn from a "
                        "separate seeded stream, so prompts stay bitwise-"
                        "identical across shapes (the autoscale A/B "
                        "fixture)")
    p.add_argument("--rate", type=float, default=0.5,
                   help="open-loop arrival rate (requests per model pass; "
                        "with --shape, the PEAK rate)")
    p.add_argument("--burst-size", type=int, default=8)
    p.add_argument("--burst-factor", type=float, default=4.0)
    p.add_argument("--concurrency", type=int, default=16,
                   help="closed-loop in-flight request count")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt-lens", default="4,16,64",
                   help="lo,typical,hi of the heavy-tail prompt mixture")
    p.add_argument("--out-lens", default="2,16,64",
                   help="lo,typical,hi of the heavy-tail output mixture")
    p.add_argument("--tail-frac", type=float, default=0.25)
    p.add_argument("--shared-prefix", default=None, metavar="G:P",
                   help="shared-prefix traffic: G prefix groups of P "
                        "tokens each; every prompt = one group's prefix + "
                        "a unique heavy-tail tail (the prefix-cache A/B "
                        "workload)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="enable the cross-request prefix cache on the "
                        "continuous policy (admissions bind cached prompt "
                        "pages and prefill only the tail; the static "
                        "baseline always runs cache-off and reports the "
                        "cache counters as 0)")
    p.add_argument("--kv-dtype", default=None,
                   choices=("float32", "bfloat16", "int8"),
                   help="KV-pool storage dtype: bfloat16 halves pool "
                        "bytes, int8 quarters them (pages quantize at the "
                        "write boundary with per-page scales + stochastic "
                        "rounding; dequant fused into the attention "
                        "kernels). The row gains a kv_dtype field; "
                        "default float32 keeps the pinned schema")
    p.add_argument("--speculative", default=None, metavar="ngram:N:K",
                   help="self-drafting speculative decoding: an N-gram "
                        "drafter proposes up to K tokens per decode row "
                        "from the row's own stream, verified in one "
                        "K+1-wide pass priced as ONE model pass; greedy "
                        "acceptance keeps token streams bitwise identical "
                        "to non-speculative. The row gains speculative/"
                        "spec_*/tokens_per_pass fields")
    p.add_argument("--scrub", type=int, default=None, metavar="N",
                   help="arm the SDC checksum ledger (serve/integrity.py) "
                        "and scrub N stamped pool pages per step (0 = "
                        "boundary verification only) — the clean-traffic "
                        "overhead measurement for the defense servechaos "
                        "exercises under --corrupt. The row gains the "
                        "sdc_* counters (all zero without injected "
                        "faults); plain rows keep the pinned schema")
    p.add_argument("--sample", default=None, metavar="temperature:T[,top-k:K]",
                   help="sample instead of greedy argmax: softmax(logits/T)"
                        " with optional top-k restriction, counter-based "
                        "per-request seeds (run seed + request id + token "
                        "index) so streams stay bitwise-reproducible; "
                        "default greedy")
    p.add_argument("--slo-ttft", type=float, default=16.0,
                   help="TTFT SLO in time units (model passes)")
    p.add_argument("--slo-itl", type=float, default=2.0,
                   help="mean inter-token-latency SLO in time units")
    p.add_argument("--deadline-slack", type=float, default=None,
                   metavar="S",
                   help="per-request completion deadline = arrival + S "
                        "time units: the engine SHEDS a request at "
                        "admission when its projected completion already "
                        "misses the deadline (named rejection; see "
                        "--retry) and cancels an expired one into the "
                        "named `timeout` terminal state with all pages "
                        "freed. The row gains shed/timeouts/retries/"
                        "rejected/requests_lost + rate fields; plain rows "
                        "keep the pinned schema")
    p.add_argument("--retry", default=None, metavar="N:B",
                   help="bounded retry-with-backoff for SHED requests: up "
                        "to N resubmissions, the k-th after B*2^k time "
                        "units — after N the request is terminally "
                        "rejected. Only meaningful with --deadline-slack")
    p.add_argument("--tier-mix", type=float, default=None, metavar="F",
                   help="SLO tiers (ROADMAP 2c): each request is drawn "
                        "tier=batch with probability F (else interactive)."
                        " Interactive admits ahead of batch and batch is "
                        "the preemptible eviction lane; the row gains "
                        "per-tier TTFT/ITL/goodput/attainment splits")
    p.add_argument("--heartbeat", type=float, default=0.0, metavar="W",
                   help="serve-side straggler heartbeat: a replica "
                        "holding work with no progress for > W time units "
                        "is drained and its requests redistribute to the "
                        "survivors (0 = off; mostly exercised by "
                        "servechaos stall injection)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="record the request-lifecycle trace (virtual-time "
                        "spans/counters, one track per request per replica)"
                        " and write Chrome trace-event JSON here — "
                        "PATH.<policy> when several policies run. Metrics-"
                        "neutral: the JSON line is bitwise identical with "
                        "or without this flag")
    p.add_argument("--trace-capacity", type=int, default=200_000,
                   help="trace ring size in events (the ring keeps the "
                        "newest window and the metadata records drops)")
    p.add_argument("--timeline", action="store_true",
                   help="with --trace: reduce the trace via telemetry/"
                        "serveview and embed the windowed SLO/goodput "
                        "table + TTFT/ITL component breakdowns in the "
                        "JSON line")
    p.add_argument("--window", type=float, default=32.0,
                   help="timeline bucket width in time units "
                        "(with --timeline)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--paged-kernel", default="dots",
                   choices=("dots", "elementwise"),
                   help="paged-kernel math formulation (ops/paged_decode)")
    p.add_argument("--wall-clock", action="store_true",
                   help="also report real elapsed seconds (off by default "
                        "so the JSON stays bitwise-reproducible)")
    p.add_argument("--audit", default=None, metavar="PATH",
                   help="emit the serve programs' compiled audit manifests "
                        "(telemetry/audit.py: flops / HBM / collective "
                        "ledger + pool_page_bytes tie-out) into one ledger "
                        "JSON next to the row")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    if args.timeline and not args.trace:
        p.error("--timeline reduces a recorded trace; pass --trace PATH")
    if args.window <= 0:
        p.error("--window must be > 0 time units")
    apply_platform(args.platform)

    import jax

    from ddlbench_tpu.distributed import (enable_compilation_cache,
                                          record_provenance)

    enable_compilation_cache()
    prov = record_provenance(args.platform, "servebench")

    from ddlbench_tpu.config import DATASETS, ServeConfig
    from ddlbench_tpu.models import init_model
    from ddlbench_tpu.models.zoo import get_model
    from ddlbench_tpu.ops.paged_decode import set_paged_kernel_style
    from ddlbench_tpu.serve.engine import make_server, supports_serve
    from ddlbench_tpu.serve.workload import make_workload
    from ddlbench_tpu.telemetry.stats import serve_summary

    spec = DATASETS[args.benchmark]
    if spec.kind != "tokens":
        p.error(f"-b {args.benchmark!r} is not a causal-LM token workload; "
                "the serving engine serves causal LMs (pick a 'tokens' "
                "benchmark, e.g. synthtext)")
    model = get_model(args.model, spec)
    if not supports_serve(model):
        p.error(f"{args.model} has layers without serving support")
    set_paged_kernel_style(args.paged_kernel)
    params, state, _ = init_model(model, jax.random.key(0))

    plo, ptyp, phi = (int(x) for x in args.prompt_lens.split(","))
    olo, otyp, ohi = (int(x) for x in args.out_lens.split(","))
    policies = [s.strip() for s in args.policies.split(",") if s.strip()]
    groups = prefix_len = 0
    if args.shared_prefix:
        try:
            groups, prefix_len = (int(x)
                                  for x in args.shared_prefix.split(":"))
        except ValueError:
            p.error("--shared-prefix wants G:P (groups:prefix_tokens), "
                    f"got {args.shared_prefix!r}")
    retry = parse_retry(args.retry, p.error)
    disagg = parse_disaggregate(args.disaggregate, p.error)
    autoscale = parse_autoscale(args.autoscale, p.error)
    if autoscale:
        if args.resize:
            p.error("--autoscale closes the resize loop itself; it does "
                    "not compose with a scripted --resize schedule")
        if args.scale_window <= 0:
            p.error("--scale-window must be > 0 time units")
        if args.scale_cooldown < 0:
            p.error("--scale-cooldown must be >= 0 time units")
    if args.shape and args.arrival != "poisson":
        p.error("--shape modulates the poisson arrival process; pass "
                "--arrival poisson")
    if args.serve_tp < 1:
        p.error("--serve-tp must be >= 1")
    if disagg:
        if policies != ["continuous"]:
            p.error("--disaggregate serves the continuous policy only "
                    "(pass --policies continuous); the static baseline's "
                    "fill/drain barrier has no phase boundary to ship at")
        if args.replicas != 1:
            p.error("--disaggregate P:D sets both fleet sizes; drop "
                    "--replicas")
        if args.resize:
            p.error("--resize scales one aggregated fleet; it does not "
                    "compose with --disaggregate")
    if args.deadline_slack is not None and args.deadline_slack <= 0:
        p.error("--deadline-slack must be > 0 time units")
    if args.retry and args.deadline_slack is None:
        p.error("--retry retries SHED submissions; nothing is ever shed "
                "without --deadline-slack")
    if args.tier_mix is not None and not 0.0 <= args.tier_mix <= 1.0:
        p.error("--tier-mix is a probability in [0, 1]")
    if args.heartbeat < 0:
        p.error("--heartbeat must be >= 0 time units (0 = off)")
    if args.scrub is not None and args.scrub < 0:
        p.error("--scrub must be >= 0 pages per step (0 arms the ledger "
                "with boundary verification only)")
    resizes = []
    for rspec in args.resize:
        try:
            at_s, n_s = rspec.split(":")
            at, nrep = float(at_s), int(n_s)
        except ValueError:
            p.error(f"--resize wants AT:N (virtual_time:replicas), "
                    f"got {rspec!r}")
        if at < 0 or nrep < 1:
            p.error(f"--resize {rspec!r}: AT >= 0 and N >= 1")
        resizes.append((at, nrep))
    resizes.sort()
    temperature, top_k = 0.0, 0
    if args.sample:
        for part in args.sample.split(","):
            key, _, val = part.partition(":")
            if key == "temperature":
                temperature = float(val)
            elif key == "top-k":
                top_k = int(val)
            else:
                p.error(f"--sample parts are temperature:T and top-k:K, "
                        f"got {part!r}")
        if temperature <= 0.0:
            p.error("--sample needs temperature:T with T > 0 "
                    "(omit --sample for greedy)")
    # under --autoscale the INITIAL fleet is --replicas clamped into the
    # band (start inside the clamps; the controller takes it from there)
    replicas0 = (max(autoscale[0], min(autoscale[1], args.replicas))
                 if autoscale else args.replicas)
    base = ServeConfig(
        max_batch=args.max_batch, pool_pages=args.pool_pages,
        page=args.page, max_len=min(args.max_len, spec.seq_len),
        token_budget=args.token_budget,
        prefill_chunk=(args.page if args.prefill_chunk is None
                       else args.prefill_chunk),
        replicas=replicas0, tp=args.serve_tp,
        temperature=temperature, top_k=top_k,
        sample_seed=args.seed, trace=bool(args.trace),
        slo_ttft=args.slo_ttft, slo_itl=args.slo_itl,
        heartbeat=args.heartbeat,
        kv_dtype=args.kv_dtype or "float32",
        speculative=args.speculative or "none",
        integrity=args.scrub is not None, scrub=args.scrub or 0)

    shared_fns = None
    rc = 0
    for policy in policies:
        # the static baseline is cache-off by definition (it measures
        # whole-batch scheduling); its JSON rows still carry the prefix
        # counters — as zeros — so the schema is stable across policies
        cfg = base.replace(
            policy=policy,
            prefix_cache=args.prefix_cache and policy == "continuous")
        cfg.validate()
        # fresh workload per policy: ServeRequest.arrival is stamped by the
        # closed-loop driver, and both policies must see identical traffic
        reqs = make_workload(
            seed=args.seed, n_requests=args.requests,
            vocab=spec.num_classes, arrival=args.arrival, rate=args.rate,
            shape=args.shape,
            burst_size=args.burst_size, burst_factor=args.burst_factor,
            prompt_lo=plo, prompt_typical=ptyp, prompt_hi=phi,
            out_lo=olo, out_typical=otyp, out_hi=ohi,
            tail_frac=args.tail_frac, prefix_groups=groups,
            prefix_len=prefix_len, max_len=cfg.max_len,
            deadline_slack=args.deadline_slack,
            batch_frac=args.tier_mix or 0.0)
        # policy rows share the compiled programs (identical model and
        # shapes — policy/prefix_cache are host-side decisions), so only
        # the first row pays the trace
        if disagg:
            from ddlbench_tpu.serve.handoff import make_disaggregated

            server = make_disaggregated(model, params, state, cfg,
                                        disagg[0], disagg[1],
                                        shared_fns=shared_fns)
        else:
            server = make_server(model, params, state, cfg,
                                 shared_fns=shared_fns)
        shared_fns = server.engines[0].jit_fns()
        controllers = None
        if autoscale:
            from ddlbench_tpu.serve.autoscaler import (
                AutoscalePolicy, combined_attainment, make_controllers,
                replica_hours)

            policy_cfg = AutoscalePolicy(
                lo=autoscale[0], hi=autoscale[1],
                window=args.scale_window,
                cooldown_up=args.scale_cooldown,
                cooldown_down=args.scale_cooldown)
            controllers = make_controllers(server, policy_cfg)
        if args.audit:
            # compiled-program audit for this serve layout: every engine
            # shares the compiled programs, so engine[0] speaks for the
            # fleet (one ledger per run; policies share shapes)
            from ddlbench_tpu.telemetry.audit import (audit_serve_engine,
                                                      write_manifests)

            mans, pool_audit = audit_serve_engine(
                server.engines[0], prefix=f"serve/{args.model}")
            write_manifests(args.audit, mans,
                            header={**prov, "tool": "servebench"})
            print(f"servebench: {len(mans)} audit manifests -> "
                  f"{args.audit} (pool_ok={pool_audit['ok']})",
                  file=sys.stderr, flush=True)
            args.audit = None
        # one fresh bounded ring per policy row, installed process-global
        # (the engines look it up lazily) and restored afterwards —
        # recording never reorders the scheduler, so the run below is
        # bitwise identical traced or not (pinned)
        tracer = prev_tracer = None
        if args.trace:
            from ddlbench_tpu.telemetry.tracer import (Tracer, get_tracer,
                                                       set_tracer)

            prev_tracer = get_tracer()
            tracer = set_tracer(Tracer(args.trace_capacity)).enable()
        dstats = {}
        t0 = time.perf_counter()
        try:
            if args.arrival == "closed":
                duration = run_closed_loop(server, reqs, args.concurrency,
                                           resizes=resizes, retry=retry,
                                           deadline_slack=args.deadline_slack,
                                           driver_stats=dstats,
                                           controllers=controllers)
            else:
                duration = run_open_loop(server, reqs, resizes=resizes,
                                         retry=retry,
                                         deadline_slack=args.deadline_slack,
                                         driver_stats=dstats,
                                         controllers=controllers)
            if controllers:
                # settle the ledgers at the final clock (integrates
                # replica-hours through any trailing idle segment)
                _advance_controllers(controllers, duration)
        finally:
            if tracer is not None:
                tracer.disable()
                set_tracer(prev_tracer)
        wall = time.perf_counter() - t0
        if resizes and len(server.resize_events) < len(resizes):
            unfired = [f"{at:g}:{n}" for at, n in
                       resizes[len(server.resize_events):]]
            print(f"servebench: WARNING {len(unfired)} --resize event(s) "
                  f"dated past the end of work never fired "
                  f"({', '.join(unfired)}); the run drained at "
                  f"{duration:g}", file=sys.stderr, flush=True)
        timeline_fields = {}
        if tracer is not None:
            from ddlbench_tpu.telemetry.export import export_chrome_trace

            if args.timeline:
                from ddlbench_tpu.telemetry.serveview import breakdown

                bd = breakdown(tracer, slo_ttft=args.slo_ttft,
                               slo_itl=args.slo_itl, window=args.window,
                               per_request=False)
                timeline_fields = {
                    "window": args.window,
                    "timeline": _round6(bd["timeline"]),
                    "ttft_breakdown": _round6(bd["ttft"]),
                    "itl_breakdown": _round6(bd["itl"]),
                    "decomp_exact": bd["decomp_exact"],
                }
            path = (args.trace if len(policies) == 1
                    else f"{args.trace}.{policy}")
            n = export_chrome_trace(tracer, path, extra_metadata={
                "serve": {"tool": "servebench", "policy": policy,
                          "tp": cfg.tp, "replicas": cfg.replicas,
                          "slo_ttft": args.slo_ttft,
                          "slo_itl": args.slo_itl,
                          "time_unit": "model_pass",
                          "seed": args.seed}})
            print(f"servebench: {n} trace events written to {path}"
                  + (f" ({tracer.dropped_events} dropped: ring full)"
                     if tracer.dropped_events else ""),
                  file=sys.stderr, flush=True)
        fin = server.finished
        summary = serve_summary(fin, duration=duration,
                                slo_ttft=args.slo_ttft,
                                slo_itl=args.slo_itl,
                                per_tier=args.tier_mix is not None)
        eng_stats = server.stats_summary()
        chaos = args.deadline_slack is not None
        sdc = args.scrub is not None
        acct = shed_accounting(args.requests, len(fin),
                               int(eng_stats["shed"]),
                               int(eng_stats["timeouts"]), dstats)
        lost = acct["requests_lost"]
        rec = {
            "tool": "servebench",
            "model": args.model,
            "benchmark": args.benchmark,
            "policy": policy,
            "arrival": args.arrival,
            # --shape only (plain rows keep the pinned schema): the
            # traffic rate curve the arrivals followed
            **({"shape": args.shape} if args.shape else {}),
            "rate": args.rate if args.arrival != "closed" else None,
            "concurrency": (args.concurrency if args.arrival == "closed"
                            else None),
            "requests": args.requests,
            "seed": args.seed,
            "max_batch": cfg.max_batch,
            "pool_pages": cfg.pool_pages,
            "page": cfg.page,
            "max_len": cfg.max_len,
            "prefill_chunk": cfg.resolved_prefill_chunk(),
            "token_budget": cfg.resolved_token_budget(),
            "replicas": cfg.replicas,
            "prefix_cache": cfg.prefix_cache,
            "shared_prefix": args.shared_prefix,
            "sample": args.sample,
            "time_unit": "model_pass",
            **{k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in summary.items()},
            **{k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in eng_stats.items()
               # serve_summary already reports completed; the speculative
               # and deadline counters are flag-gated (all zero when the
               # flags are off) so a plain row keeps the schema-pinned
               # key set
               if k != "completed"
               and (args.speculative or k not in _SPEC_FIELDS)
               and (chaos or k not in _CHAOS_FIELDS)
               and (disagg or k not in _DISAGG_FIELDS)
               and (sdc or k not in _SDC_FIELDS)},
            # --serve-tp only (plain rows keep the pinned schema): the
            # tp-group width every replica runs at
            **({"serve_tp": cfg.tp} if args.serve_tp > 1 else {}),
            # --disaggregate only: the fleet split (shipped_* counters
            # ride the stats merge above under the same gate)
            **({"disaggregate": args.disaggregate,
                "prefill_replicas": disagg[0],
                "decode_replicas": disagg[1]} if disagg else {}),
            # --kv-dtype / --speculative only (plain rows keep the
            # schema-pinned key set): the A/B axis made explicit
            **({"kv_dtype": cfg.kv_dtype} if args.kv_dtype else {}),
            **({"speculative": cfg.speculative}
               if args.speculative else {}),
            # --scrub only (plain rows keep the schema-pinned key set):
            # the scrub budget behind the sdc_* counters riding the
            # stats merge above
            **({"scrub": cfg.scrub} if sdc else {}),
            # --timeline only: windowed SLO/goodput series + TTFT/ITL
            # component breakdowns (absent otherwise so a plain row stays
            # bitwise identical traced or untraced)
            **timeline_fields,
            # --deadline-slack only (plain rows keep the schema-pinned
            # key set): the deadline knob, the driver's retry policy
            # outcome, and the shed/timeout economics as rates
            **({"deadline_slack": args.deadline_slack,
                "retry": args.retry, **acct}
               if chaos else {}),
            # --tier-mix only: the per-tier summary split rides the
            # serve_summary merge above; this records the mix itself
            **({"tier_mix": args.tier_mix}
               if args.tier_mix is not None else {}),
            # --heartbeat only: straggler drains (servechaos's stall
            # injections are where these fire)
            **({"heartbeat": args.heartbeat,
                "heartbeat_drains": len(server.heartbeat_events)}
               if args.heartbeat else {}),
            # --resize only (plain rows keep the schema-pinned key set):
            # the resize schedule, what each event displaced, the final
            # fleet size, and the no-request-lost invariant made explicit
            **({"resize": args.resize,
                "resize_events": server.resize_events,
                # schedule entries dated past the end of work never fire
                # (the drivers only resize while work remains) — surfaced
                # rather than silently compared against a fleet that
                # never reached its scheduled size
                "resizes_unfired": len(resizes) - len(server.resize_events),
                "final_replicas": len(server.engines),
                "requests_lost": lost}
               if args.resize else {}),
            # --autoscale only (plain rows keep the schema-pinned key
            # set): the closed-loop economics — replica-hours actually
            # consumed (the static baseline pays replicas * duration),
            # every decision with its triggering signal, and the
            # no-loss invariant the tool's exit code gates on
            **({"autoscale": args.autoscale,
                "scale_window": args.scale_window,
                "scale_cooldown": args.scale_cooldown,
                "replica_hours": round(replica_hours(controllers), 6),
                "scale_events": sum(c.scale_events for c in controllers),
                "repairs": sum(c.repairs for c in controllers),
                "autoscale_attainment": round(
                    combined_attainment(controllers), 6),
                "autoscale_events": _round6(
                    [e for c in controllers for e in c.events]),
                "final_replicas": len(server.engines),
                "requests_lost": lost}
               if autoscale else {}),
            # actual backend record (shared classification —
            # distributed.backend_provenance); cpu-fallback rows must be
            # identifiable as harness validation, not chip numbers
            **prov,
        }
        if args.wall_clock:
            rec["wall_s"] = round(wall, 3)
        print(json.dumps(rec), flush=True)
        if autoscale and lost != 0:
            # the no-loss gate extends from the chaos tools to the
            # controller path: a self-scaling fleet that loses requests
            # is a broken controller, and CI must see it
            print(f"servebench: FAILED no-loss gate under --autoscale: "
                  f"requests_lost={lost} on policy {policy}",
                  file=sys.stderr, flush=True)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
