"""Per-layer model summary tables for every model x dataset.

Parity with the reference's run/summary harness + benchmark/network_summary.py
(torchsummary dump of each model on CPU as a shape sanity check,
network_summary.py:27-111). Shape inference here is exact and free: the layer
chain's init computes the boundary shapes without running a forward pass.

Usage:
    python -m ddlbench_tpu.tools.summary                    # full matrix
    python -m ddlbench_tpu.tools.summary -m resnet18 -b mnist
"""

from __future__ import annotations

import argparse
import sys

import jax

from ddlbench_tpu.config import DATASETS
from ddlbench_tpu.models.layers import param_count
from ddlbench_tpu.models.zoo import MODEL_NAMES, get_model
from ddlbench_tpu.models import init_model


def summarize(arch: str, benchmark: str) -> str:
    model = get_model(arch, benchmark)
    params_list, _, shapes = init_model(model, jax.random.key(0))
    lines = [
        f"== {arch} / {benchmark} (input {shapes[0]}) ==",
        f"{'layer':<24}{'output shape':<20}{'params':>12}",
        "-" * 56,
    ]
    total = 0
    for layer, p, out_shape in zip(model.layers, params_list, shapes[1:]):
        n = param_count(p)
        total += n
        lines.append(f"{layer.name:<24}{str(out_shape):<20}{n:>12,}")
    lines.append("-" * 56)
    lines.append(f"{'total':<44}{total:>12,}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model", default=None, choices=MODEL_NAMES)
    p.add_argument("-b", "--benchmark", default=None, choices=sorted(DATASETS))
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)
    models = [args.model] if args.model else MODEL_NAMES
    benchmarks = [args.benchmark] if args.benchmark else sorted(DATASETS)
    explicit = bool(args.model and args.benchmark)
    for arch in models:
        for b in benchmarks:
            try:
                out = summarize(arch, b)
            except ValueError:
                # incompatible pair (image arch x token dataset etc.): matrix
                # mode skips it; an explicitly requested pair still errors
                if explicit:
                    raise
                continue
            print(out)
            print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
