"""Synthetic on-disk dataset factory CLI.

Parity with benchmark/generate_synthetic_data.py (multiprocess pool writing
random JPEGs, :49-71): writes raw uint8 tensor stores for any of the four
dataset blueprints via the multithreaded native generator.

    python -m ddlbench_tpu.tools.generate_data -b mnist -o ./data
    python -m ddlbench_tpu.tools.generate_data -b imagenet -o ./data --train-count 10000
"""

from __future__ import annotations

import argparse
import sys
import time

from ddlbench_tpu.config import DATASETS
from ddlbench_tpu.data.native_loader import generate_dataset


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-b", "--benchmark", required=True, choices=sorted(DATASETS))
    p.add_argument("-o", "--out", default="./data")
    p.add_argument("--train-count", type=int, default=None)
    p.add_argument("--test-count", type=int, default=None)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--threads", type=int, default=4)
    args = p.parse_args(argv)
    spec = DATASETS[args.benchmark]
    for split, count in (("train", args.train_count), ("test", args.test_count)):
        t0 = time.perf_counter()
        out = generate_dataset(args.out, spec, split, count=count,
                               seed=args.seed, threads=args.threads)
        n = count or (spec.train_size if split == "train" else spec.test_size)
        print(f"{split}: {n} samples -> {out} ({time.perf_counter() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
