"""Serving-fleet chaos benchmark: replica kills, stragglers, deadlines.

chaosbench (tools/chaosbench.py) made TRAINING failure a benchmark
dimension — kill/preempt/shrink/grow under a supervisor, with bitwise
resume as the pass/fail gate. This is its SERVING sibling: it drives the
continuous-batching fleet (serve/engine.py ReplicatedServer) with a
seeded servebench workload while injecting replica faults, and reports
recovery as numbers with the same repro discipline — one JSON line,
bitwise-reproducible in virtual time (1 unit = 1 model pass).

Faults (virtual-time schedule, repeatable flags):

* ``--kill T:R``  — HARD-KILL the replica at fleet index R at time T:
  its pool (all resident KV) is lost, finished records are salvaged, and
  every request it held is resubmitted least-loaded onto the survivors,
  where eviction/recompute regenerates the token streams from scratch.
  The gates: ``requests_lost == 0`` (every request reaches a terminal
  state) and ``streams_match`` — the failed-over streams are BITWISE
  equal to an unfaulted control run of the same workload (greedy/seeded
  sampling are pure functions of (params, prompt, rid, token index) —
  the PR 12 resize argument, now under uncoordinated loss).
* ``--stall T:R:D`` — STRAGGLER: the replica stops progressing for D
  global steps while holding its requests (grey failure — nothing died).
  With ``--heartbeat W`` the serve-side no-progress detector
  (train/watchdog.ProgressMonitor on the virtual clock) drains it within
  the detection window and redistributes its requests like a scale-down.
* ``--deadline-slack S`` / ``--retry N:B`` / ``--tier-mix F`` — the
  deadline + SLO-tier load shape (shared with servebench): expired
  requests cancel into the named ``timeout`` terminal state, admission
  SHEDS requests whose projected completion already misses the deadline,
  the driver retries sheds with bounded backoff, and interactive traffic
  admits ahead of (and preempts) the batch tier.
* ``--corrupt T:R:TARGET[@L.S]`` — SILENT DATA CORRUPTION: flip one real
  bit at virtual time T in replica R's serving data plane
  (serve/integrity.py). TARGET picks the victim: ``payload`` (a settled
  KV pool page), ``sidecar`` (an int8 scale row — needs --kv-dtype
  int8), ``prefix`` (a prefix-cache-shared page: every referencing
  request read poisoned bytes), or ``ship`` (an in-flight handoff
  payload — needs --disaggregate; R must be 0, the wire has no replica
  index). ``@L.S`` optionally pins the model layer and pool slot;
  omitted, a deterministic settled resident page is picked at fire
  time. Arming any --corrupt turns the checksum ledger ON
  (cfg.integrity) unless ``--no-detect`` asks for the honest
  no-defense measurement; ``--scrub N`` budgets the background
  scrubber at N pages/step (default: a full sweep when detection is
  armed). The headline gate mirrors --kill's: with detection on, token
  streams pin BITWISE vs the unfaulted control and
  ``requests_lost == 0`` (detection -> quarantine -> re-prefill
  regenerates int8 pages byte-identically); with --no-detect the row
  reports the ESCAPED divergence instead of hiding it.

Reported: ``mttr_replica_s`` — per kill, the virtual time from the kill
until the LAST displaced in-flight request emits its first post-failover
token (mean over kills; the ``_s`` suffix keeps chaosbench's field-naming
symmetry, but the unit is model passes unless you read ``wall_s``) —
plus ``requests_lost`` (gate: 0 for failover-covered kills),
``streams_match``/``streams_diverged`` vs the unfaulted control,
shed/timeout/retry rates, per-tier SLO attainment, heartbeat drains, and
the final fleet size.

Self-healing (ISSUE 19): ``--autoscale LO:HI`` runs the same faults
under an ACTIVE FleetController (serve/autoscaler.py) — a killed or
heartbeat-drained replica is auto-repaired through the factory spawn,
so MTTR becomes a controller property. The tool then ALSO runs the
scripted-recovery baseline (same faults, no controller — the PR 15
behavior) when that schedule survives a non-repairing fleet, and
reports ``mttr_scripted_*`` next to ``mttr_replica_s*`` plus the
``repair_mttr_le_scripted`` verdict; the headline gate is
``requests_lost == 0`` AND auto-repair MTTR <= scripted MTTR.

Usage:
    python -m ddlbench_tpu.tools.servechaos [-m transformer_s]
        [-b synthtext] [--replicas 2] [--kill 12:1] [--stall 8:0:6]
        [--corrupt 10:0:payload] [--no-detect] [--scrub 4]
        [--heartbeat 4] [--deadline-slack 32] [--retry 2:4]
        [--tier-mix 0.5] [--autoscale 2:2] [--arrival poisson|closed]
        [--rate 0.5] [--requests 64] [--no-control] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _parse_kills(specs, perr, disagg=False):
    """Kill specs as (t, fleet, index) triples. Aggregated grammar is
    ``T:R`` (fleet None); under --disaggregate the index names its fleet:
    ``T:pR`` kills prefill replica R, ``T:dR`` decode replica R."""
    out = []
    for s in specs:
        try:
            t_s, r_s = s.split(":")
            if disagg:
                fleet = r_s[:1]
                if fleet not in ("p", "d") or not r_s[1:]:
                    raise ValueError
                out.append((float(t_s), fleet, int(r_s[1:])))
            else:
                out.append((float(t_s), None, int(r_s)))
        except ValueError:
            if disagg:
                perr(f"--kill under --disaggregate wants T:pR or T:dR "
                     f"(virtual_time:fleet+index), got {s!r}")
            perr(f"--kill wants T:R (virtual_time:fleet_index), got {s!r}")
        if out[-1][0] < 0 or out[-1][2] < 0:
            perr(f"--kill {s!r}: T >= 0 and R >= 0")
    return out


_CORRUPT_TARGETS = ("payload", "sidecar", "prefix", "ship")


def _parse_corrupts(specs, perr, disagg=False):
    """Corrupt specs as (t, fleet, index, target, layer, slot) tuples.
    Grammar ``T:R:TARGET[@L.S]``; under --disaggregate pool targets name
    their fleet like --kill (``T:pR:...`` / ``T:dR:...``) while the
    ``ship`` target keeps ``T:0:ship`` — the wire has no replica index."""
    out = []
    for s in specs:
        try:
            t_s, r_s, rest = s.split(":", 2)
            layer = slot = None
            if "@" in rest:
                tgt, at = rest.split("@", 1)
                l_s, p_s = at.split(".")
                layer, slot = int(l_s), int(p_s)
            else:
                tgt = rest
            t = float(t_s)
            if disagg and tgt != "ship":
                fleet = r_s[:1]
                if fleet not in ("p", "d") or not r_s[1:]:
                    raise ValueError
                r = int(r_s[1:])
            else:
                fleet, r = None, int(r_s)
            out.append((t, fleet, r, tgt, layer, slot))
        except ValueError:
            if disagg:
                perr(f"--corrupt under --disaggregate wants "
                     f"T:pR:TARGET[@L.S], T:dR:TARGET[@L.S] or T:0:ship, "
                     f"got {s!r}")
            perr(f"--corrupt wants T:R:TARGET[@LAYER.SLOT] "
                 f"(virtual_time:fleet_index:target), got {s!r}")
        t, fleet, r, tgt, layer, slot = out[-1]
        if tgt not in _CORRUPT_TARGETS:
            perr(f"--corrupt {s!r}: target must be one of "
                 f"{'/'.join(_CORRUPT_TARGETS)}, got {tgt!r}")
        if t < 0 or r < 0:
            perr(f"--corrupt {s!r}: T >= 0 and R >= 0")
        if slot is not None and slot < 1:
            perr(f"--corrupt {s!r}: slot 0 is the scratch page (it holds "
                 f"no request data); slots start at 1")
        if layer is not None and layer < 0:
            perr(f"--corrupt {s!r}: layer must be >= 0")
    return out


def _pick_slot(eng, target):
    """Deterministic fire-time victim: a SETTLED resident page (below
    every active row's write frontier — a flip into the page about to be
    appended to races the next write's re-stamp, which would bless the
    corruption; see integrity.stable_stamped_slots). For ``prefix`` the
    victim is a prefix-indexed page, shared (refcount >= 2) when one
    exists. Returns None when nothing is resident yet."""
    if target == "prefix":
        idx = sorted(set(eng.prefix._slots.values()))
        shared = [s for s in idx if eng.allocator.refcount(s) >= 2]
        return (shared or idx or [None])[0]
    hot, cand = set(), []
    for a in eng._active():
        if a.state == "decode":
            p0 = a.decode_pos // eng.page
            for i in range(a.n_pages):
                s = int(eng.table[a.row, i])
                (hot.add(s) if i >= p0 else cand.append(s))
        else:
            fp = a.prefill_done // eng.page
            for i in range(min(a.n_pages, fp)):
                cand.append(int(eng.table[a.row, i]))
            if fp < a.n_pages:
                hot.add(int(eng.table[a.row, fp]))
    picks = sorted(set(cand) - hot - {0})
    if eng.integrity is not None:
        stamped = set(eng.integrity.stamped_slots())
        picks = [s for s in picks if s in stamped]
    return picks[0] if picks else None


def _parse_stalls(specs, perr):
    out = []
    for s in specs:
        try:
            t_s, r_s, d_s = s.split(":")
            out.append((float(t_s), int(r_s), int(d_s)))
        except ValueError:
            perr(f"--stall wants T:R:D (time:fleet_index:ticks), got {s!r}")
        if out[-1][0] < 0 or out[-1][1] < 0 or out[-1][2] < 1:
            perr(f"--stall {s!r}: T >= 0, R >= 0, D >= 1")
    return out


def _fault_events(kills, stalls):
    """The drivers' timed-injection schedule: kills and stalls as
    ``(at, fn(server, clock))`` closures (tools/servebench._fire_events).
    Fleet indices are resolved AT FIRE TIME — a kill shrinks the fleet,
    so later specs address the surviving fleet's positions."""
    ev = []

    def kill_fn(fleet, r):
        def fire(server, clock):
            if fleet == "p":
                rep = server.fail_prefill(r, now=clock)
            elif fleet == "d":
                rep = server.fail_decode(r, now=clock)
            else:
                rep = server.fail(r, now=clock)
            which = {"p": "prefill ", "d": "decode "}.get(fleet, "")
            print(f"servechaos: kill @ {clock:g} -> {which}replica "
                  f"{rep['replica_id']} (salvaged {rep['salvaged']}, "
                  f"displaced {len(rep['displaced_inflight'])} in-flight "
                  f"+ {rep['displaced_queued']} queued)",
                  file=sys.stderr, flush=True)
        return fire

    def stall_fn(r, d):
        def fire(server, clock):
            server.stall(r, d, now=clock)
            print(f"servechaos: stall @ {clock:g} -> replica index {r} "
                  f"for {d} steps", file=sys.stderr, flush=True)
        return fire

    for t, fleet, r in kills:
        ev.append((t, kill_fn(fleet, r)))
    for t, r, d in stalls:
        ev.append((t, stall_fn(r, d)))
    ev.sort(key=lambda e: e[0])
    return ev


def _corrupt_events(corrupts, fired):
    """SDC injections as ``(at, fn(server, clock))`` closures. Each fire
    flips ONE real bit (serve/integrity.py flip helpers) and appends a
    record to ``fired`` — a fire that finds no resident victim (pool
    still empty at T) records nothing and warns, so ``corrupts_fired``
    stays honest. Byte 3 / bit 6 of the first element lands in the f32
    exponent (and flips an int8 payload value by 64): big enough that an
    ESCAPED flip visibly diverges the argmax stream instead of hiding in
    low mantissa bits."""
    from ddlbench_tpu.serve import integrity as I

    def corrupt_fn(spec):
        t, fleet, r, tgt, layer, slot = spec

        def fire(server, clock):
            if tgt == "ship":
                def hook(ship):
                    if server.wire_fault_hook is not hook:
                        return  # one-shot: a later spec re-armed it
                    li = (layer if layer is not None else
                          I.pool_layers(server.prefill.engines[0])[0])
                    rec = I.flip_ship_bit(ship, layer=li, index=3, bit=6)
                    fired.append({"t": clock, "target": tgt,
                                  "rid": ship["rid"], **rec})
                    server.wire_fault_hook = None
                    print(f"servechaos: corrupt @ {clock:g} -> in-flight "
                          f"ship rid {ship['rid']} layer {rec['layer']} "
                          f"(bit {rec['bit']} of byte {rec['byte']})",
                          file=sys.stderr, flush=True)
                server.wire_fault_hook = hook
                return
            if fleet == "p":
                eng = server.prefill.engines[r]
            elif fleet == "d":
                eng = server.decode.engines[r]
            else:
                eng = server.engines[r]
            li = layer if layer is not None else I.pool_layers(eng)[0]
            key = "scale_k" if tgt == "sidecar" else None
            s = slot if slot is not None else _pick_slot(eng, tgt)
            if s is None:
                print(f"servechaos: WARNING corrupt @ {clock:g} "
                      f"({tgt}): no settled resident page to flip yet — "
                      f"injection skipped", file=sys.stderr, flush=True)
                return
            rec = I.flip_pool_bit(eng, li, s, key=key, index=3, bit=6)
            eng.stats["sdc_injected"] += 1
            fired.append({"t": clock, "target": tgt, **rec})
            print(f"servechaos: corrupt @ {clock:g} -> {tgt} layer "
                  f"{rec['layer']} slot {rec['slot']} key {rec['key']} "
                  f"(bit {rec['bit']} of byte {rec['byte']}, refcount "
                  f"{eng.allocator.refcount(s)})",
                  file=sys.stderr, flush=True)
        return fire

    return [(spec[0], corrupt_fn(spec)) for spec in corrupts]


def _run(server, reqs, args, retry, events=None, driver_stats=None,
         controllers=None):
    from ddlbench_tpu.tools.servebench import run_closed_loop, run_open_loop

    if args.arrival == "closed":
        dur = run_closed_loop(server, reqs, args.concurrency,
                              events=events, retry=retry,
                              deadline_slack=args.deadline_slack,
                              driver_stats=driver_stats,
                              controllers=controllers)
    else:
        dur = run_open_loop(server, reqs, events=events, retry=retry,
                            deadline_slack=args.deadline_slack,
                            driver_stats=driver_stats,
                            controllers=controllers)
    for c in controllers or ():
        c.advance(dur)  # settle ledgers/replica-hours at the final clock
    return dur


def _static_walk_ok(kills, sizes):
    """Would this kill schedule survive on a fleet with NO repair (every
    kill permanently shrinks its fleet)? — the feasibility check for the
    scripted-recovery baseline run under --autoscale."""
    sizes = dict(sizes)
    for t, fleet, r in sorted(kills, key=lambda k: k[0]):
        if sizes[fleet] <= 1 or r >= sizes[fleet]:
            return False
        sizes[fleet] -= 1
    return True


def mttr_from_events(fail_events, finished):
    """Per-kill recovery: the virtual time from the kill instant until
    the LAST displaced in-flight request emitted its first post-failover
    token (its replay's ``first_token_t`` — the failover stream restarts
    from scratch, so that IS the post-kill first emission). Displaced
    requests that never completed (timed out / shed on failover) are
    excluded from that kill's sample; a kill with no recoverable sample
    reports None."""
    fin = {f["rid"]: f for f in finished}
    out = []
    for ev in fail_events:
        recov = [fin[rid]["first_token_t"] - ev["t"]
                 for rid in ev["displaced_inflight"] if rid in fin]
        out.append(max(recov) if recov else None)
    return out


def _sdc_block(args, corrupts, fired, detect, cfg, server, fin, control,
               streams_diverged, acct):
    """The --corrupt row fields (spread AFTER the engine-stats spread so
    the tool-counted ``sdc_injected`` — which includes wire injections no
    engine's stats can see — wins over the fleet sum). ``sdc_escaped``
    is derived from OBSERVED outcomes, never from injected-minus-detected
    arithmetic: a flip the next write legitimately overwrote hurt nobody,
    while a flip that reached a stream shows up as divergence or loss."""
    if not corrupts:
        return {}
    from ddlbench_tpu.tools.servebench import _round6

    sdc_evs = server.sdc_events
    fin_by = {f["rid"]: f for f in fin}
    # MTTD: each injection paired with the first detection at/after it
    mttds = []
    for f_ev in fired:
        det = [ev["t"] for ev in sdc_evs if ev["t"] >= f_ev["t"]]
        mttds.append(round(min(det) - f_ev["t"], 6) if det else None)
    mttd_ok = [m for m in mttds if m is not None]
    # quarantine MTTR: per detection that displaced requests, the virtual
    # time until the LAST displaced request's recovered stream re-emitted
    # its first token (mttr_from_events' definition, on the SDC events)
    mttr_sdc = []
    for ev in sdc_evs:
        disp = ev.get("displaced") or []
        if not disp:
            continue
        recov = [fin_by[rid]["first_token_t"] - ev["t"]
                 for rid in disp if rid in fin_by]
        mttr_sdc.append(round(max(recov), 6) if recov else None)
    mttr_ok = [m for m in mttr_sdc if m is not None]
    return {
        "corrupt": args.corrupt,
        "sdc_detect": detect,
        "scrub": cfg.scrub,
        "corrupts_fired": len(fired),
        "corrupt_events": _round6(fired),
        "sdc_injected": len(fired),
        "sdc_escaped": (None if control is None else
                        streams_diverged + acct["requests_lost"]),
        "sdc_events": _round6(sdc_evs),
        "mttd_sdc": mttds,
        "mttd_sdc_mean": (round(sum(mttd_ok) / len(mttd_ok), 6)
                          if mttd_ok else None),
        "mttr_sdc_s": mttr_sdc,
        "mttr_sdc_s_mean": (round(sum(mttr_ok) / len(mttr_ok), 6)
                            if mttr_ok else None),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", default="transformer_s")
    p.add_argument("-b", "--benchmark", default="synthtext")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--disaggregate", default=None, metavar="P:D",
                   help="chaos the disaggregated layout (serve/handoff): "
                        "a P-replica prefill fleet feeding a D-replica "
                        "decode fleet by KV-page shipping. Replaces "
                        "--replicas; --kill takes T:pR / T:dR to name the "
                        "fleet (a decode kill re-routes its requests "
                        "through the prefill fleet — re-prefill "
                        "re-quantizes the pages byte-identically and the "
                        "handoff re-ships)")
    p.add_argument("--kill", action="append", default=[], metavar="T:R",
                   help="hard-kill the replica at fleet index R at "
                        "virtual time T (repeatable; pool lost, records "
                        "salvaged, requests failed over bitwise). Under "
                        "--disaggregate: T:pR (prefill) / T:dR (decode)")
    p.add_argument("--corrupt", action="append", default=[],
                   metavar="T:R:TARGET[@L.S]",
                   help="flip one bit at virtual time T in replica R's "
                        "data plane (repeatable). TARGET: payload | "
                        "sidecar (int8 scale row, needs --kv-dtype int8) "
                        "| prefix (a prefix-cache-shared page) | ship "
                        "(in-flight handoff payload, needs --disaggregate "
                        "and R=0). @L.S pins model layer + pool slot; "
                        "omitted, a settled resident page is picked at "
                        "fire time. Arms the checksum ledger "
                        "(cfg.integrity) unless --no-detect")
    p.add_argument("--no-detect", action="store_true",
                   help="run --corrupt WITHOUT the checksum ledger: the "
                        "honest no-defense measurement — the row reports "
                        "the escaped stream divergence instead of "
                        "recovery")
    p.add_argument("--scrub", type=int, default=None, metavar="N",
                   help="background-scrubber budget in pages/step "
                        "(needs --corrupt; default: a full pool sweep "
                        "per step when detection is armed)")
    p.add_argument("--stall", action="append", default=[], metavar="T:R:D",
                   help="straggler: replica at fleet index R makes no "
                        "progress for D global steps starting at time T "
                        "(repeatable; pairs with --heartbeat)")
    p.add_argument("--heartbeat", type=float, default=0.0, metavar="W",
                   help="no-progress detection window in time units: a "
                        "stalled replica holding work is drained after W "
                        "(0 = no detection; the stall just delays)")
    p.add_argument("--deadline-slack", type=float, default=None, metavar="S",
                   help="per-request completion deadline = arrival + S "
                        "(expired -> named `timeout`; hopeless at "
                        "admission -> named `shed`)")
    p.add_argument("--retry", default=None, metavar="N:B",
                   help="driver retry policy for shed requests: N "
                        "retries, k-th after B*2^k time units")
    p.add_argument("--tier-mix", type=float, default=None, metavar="F",
                   help="fraction of requests in the preemptible `batch` "
                        "tier (interactive admits ahead, batch evicts "
                        "first; per-tier SLO split reported)")
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--pool-pages", type=int, default=64)
    p.add_argument("--page", type=int, default=16)
    p.add_argument("--max-len", type=int, default=256)
    p.add_argument("--prefill-chunk", type=int, default=None)
    p.add_argument("--token-budget", type=int, default=0)
    p.add_argument("--arrival", default="poisson",
                   choices=("poisson", "bursty", "closed"))
    p.add_argument("--rate", type=float, default=0.5)
    p.add_argument("--burst-size", type=int, default=8)
    p.add_argument("--burst-factor", type=float, default=4.0)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--prompt-lens", default="4,16,64")
    p.add_argument("--out-lens", default="2,16,64")
    p.add_argument("--tail-frac", type=float, default=0.25)
    p.add_argument("--slo-ttft", type=float, default=16.0)
    p.add_argument("--slo-itl", type=float, default=2.0)
    p.add_argument("--prefix-cache", action="store_true",
                   help="enable the cross-request prefix cache "
                        "(serve/prefix.py) — required by the `prefix` "
                        "--corrupt target, which flips a bit in a "
                        "cache-shared page")
    p.add_argument("--shared-prefix", default=None, metavar="G:P",
                   help="shared-prefix workload mode (servebench's flag): "
                        "prompts draw from G groups sharing a P-token "
                        "prefix — with --prefix-cache this is what gives "
                        "the `prefix` --corrupt target a genuinely SHARED "
                        "page (refcount >= 2) to flip, so the quarantine "
                        "walk recovers several holders at once")
    p.add_argument("--kv-dtype", default=None,
                   choices=("float32", "bfloat16", "int8"))
    p.add_argument("--speculative", default=None, metavar="ngram:N:K")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--autoscale", default=None, metavar="LO:HI",
                   help="run the faults under an ACTIVE FleetController "
                        "(serve/autoscaler.py): a killed or heartbeat-"
                        "drained replica is auto-repaired through the "
                        "factory spawn, so MTTR is a controller property. "
                        "Adds a scripted-recovery BASELINE run (same "
                        "faults, no controller — the PR 15 behavior) when "
                        "the schedule survives a non-repairing fleet; the "
                        "row gains repairs/replica_hours/autoscale_events "
                        "+ mttr_scripted_* and the repair-vs-scripted "
                        "MTTR verdict")
    p.add_argument("--scale-window", type=float, default=32.0, metavar="W",
                   help="autoscale observation-window width in time units")
    p.add_argument("--scale-cooldown", type=float, default=64.0,
                   metavar="C",
                   help="min time between same-direction scale actuations "
                        "(repairs are exempt)")
    p.add_argument("--no-control", action="store_true",
                   help="skip the unfaulted control run (streams_match "
                        "reported as null)")
    p.add_argument("--wall-clock", action="store_true")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    from ddlbench_tpu.tools.servebench import (parse_autoscale,
                                               parse_disaggregate,
                                               parse_retry)

    disagg = parse_disaggregate(args.disaggregate, p.error)
    kills = _parse_kills(args.kill, p.error, disagg=bool(disagg))
    stalls = _parse_stalls(args.stall, p.error)
    corrupts = _parse_corrupts(args.corrupt, p.error, disagg=bool(disagg))
    retry = parse_retry(args.retry, p.error)
    autoscale = parse_autoscale(args.autoscale, p.error)
    if args.no_detect and not corrupts:
        p.error("--no-detect needs --corrupt (there is nothing to not "
                "detect)")
    if args.scrub is not None:
        if args.scrub < 0:
            p.error("--scrub must be >= 0 pages/step")
        if not corrupts:
            p.error("--scrub needs --corrupt (measure clean scrub "
                    "overhead with servebench --scrub instead)")
        if args.no_detect and args.scrub:
            p.error("--scrub needs the checksum ledger; drop --no-detect")
    for t, fleet, r, tgt, layer, slot in corrupts:
        if tgt == "ship":
            if not disagg:
                p.error(f"--corrupt {t:g}:{r}:ship: the ship target "
                        f"corrupts an in-flight handoff payload — it "
                        f"needs --disaggregate")
            if r != 0:
                p.error(f"--corrupt {t:g}:{r}:ship: the wire has no "
                        f"replica index; use T:0:ship")
        if tgt == "sidecar" and (args.kv_dtype or "float32") != "int8":
            p.error(f"--corrupt {t:g}:...:sidecar: the scale sidecar "
                    f"only exists for --kv-dtype int8")
        if tgt == "prefix" and not args.prefix_cache:
            p.error(f"--corrupt {t:g}:...:prefix: the prefix target "
                    f"flips a cache-shared page — it needs "
                    f"--prefix-cache")
        if slot is not None and slot >= args.pool_pages:
            p.error(f"--corrupt @{layer}.{slot}: slot {slot} out of "
                    f"range for --pool-pages {args.pool_pages} "
                    f"(valid slots: 1..{args.pool_pages - 1})")
    if autoscale:
        if args.scale_window <= 0:
            p.error("--scale-window must be > 0 time units")
        if args.scale_cooldown < 0:
            p.error("--scale-cooldown must be >= 0 time units")
    if disagg and stalls:
        p.error("--stall addresses one aggregated fleet; it does not "
                "compose with --disaggregate")
    if args.deadline_slack is not None and args.deadline_slack <= 0:
        p.error("--deadline-slack must be > 0 time units")
    if args.retry and args.deadline_slack is None:
        p.error("--retry needs --deadline-slack (nothing else sheds)")
    if args.tier_mix is not None and not 0.0 <= args.tier_mix <= 1.0:
        p.error("--tier-mix is a probability in [0, 1]")
    if args.heartbeat < 0:
        p.error("--heartbeat must be >= 0 (0 = off)")
    if not disagg and args.replicas < 2 and kills:
        p.error("--kill needs --replicas >= 2 (a survivor to fail over to)")
    # statically hopeless schedules die HERE, not with an uncaught
    # traceback after the control run already burned its compiles: every
    # kill GUARANTEES its fleet shrinks by one, so walking the kill
    # schedule in time order bounds each spec's valid indices exactly
    # (heartbeat drains can still shrink the fleet below a later spec's
    # index at runtime — fail() raises loudly in that case)
    sizes = ({"p": disagg[0], "d": disagg[1]} if disagg
             else {None: args.replicas})
    scripted_ok = _static_walk_ok(kills, sizes)
    if not autoscale:
        # sort by time ONLY (stable): equal-time kills fire in spec order
        # at runtime, and tuple-sorting by (t, index) would walk a
        # different order and falsely reject e.g. `--kill 5:2 --kill 5:0`
        for t, fleet, r in sorted(kills, key=lambda k: k[0]):
            name = {"p": "prefill ", "d": "decode "}.get(fleet, "")
            if sizes[fleet] <= 1:
                # a decode fleet must also keep a survivor: its pages can
                # be regenerated via the prefill fleet, but ships need at
                # least one live decode replica to bind into
                p.error(f"--kill {t:g}:{fleet or ''}{r}: the {name}fleet "
                        f"is already down to its last replica by t={t:g}")
            if r >= sizes[fleet]:
                p.error(f"--kill {t:g}:{fleet or ''}{r}: {name}fleet "
                        f"index {r} out of range — at most {sizes[fleet]} "
                        f"replicas remain by t={t:g}")
            sizes[fleet] -= 1
        for t, r, d in stalls:
            # a stall's valid indices also shrink with every kill that
            # fires before (or, by the event sort's kill-first tie-break,
            # at) it
            size_at_t = args.replicas - sum(1 for kt, _, _ in kills
                                            if kt <= t)
            if r >= size_at_t:
                p.error(f"--stall {t:g}:{r}:{d}: fleet index {r} out of "
                        f"range — at most {size_at_t} replicas remain by "
                        f"t={t:g} ({args.replicas} replicas, kills before "
                        f"it)")
    else:
        # under a repairing controller the fleet RE-GROWS between faults,
        # so the shrink-walk above is wrong; each spec just has to address
        # the full fleet (a too-fast second kill that beats its repair
        # still fails loudly at fire time — fail() raises)
        for t, fleet, r in kills:
            if r >= sizes[fleet]:
                name = {"p": "prefill ", "d": "decode "}.get(fleet, "")
                p.error(f"--kill {t:g}:{fleet or ''}{r}: {name}fleet "
                        f"index {r} out of range for a {sizes[fleet]}-"
                        f"replica fleet")
        for t, r, d in stalls:
            if r >= args.replicas:
                p.error(f"--stall {t:g}:{r}:{d}: fleet index {r} out of "
                        f"range for a {args.replicas}-replica fleet")
    # corrupt specs address the KILL-WALKED fleet: a replica dead by T
    # cannot host a bit-flip (under --autoscale repairs re-grow the
    # fleet, so only the full-size bound applies — like --kill's walk)
    for t, fleet, r, tgt, layer, slot in corrupts:
        if tgt == "ship":
            continue
        full = ({"p": disagg[0], "d": disagg[1]} if disagg
                else {None: args.replicas})[fleet]
        dead = (0 if autoscale else
                sum(1 for kt, kf, _ in kills if kf == fleet and kt <= t))
        if r >= full - dead:
            name = {"p": "prefill ", "d": "decode "}.get(fleet, "")
            p.error(f"--corrupt {t:g}:{fleet or ''}{r}:{tgt}: "
                    f"{name}fleet index {r} out of range — at most "
                    f"{full - dead} replicas remain by t={t:g}")
    if stalls and not args.heartbeat:
        print("servechaos: WARNING --stall without --heartbeat: the "
              "straggler is never detected, its requests just wait it "
              "out", file=sys.stderr, flush=True)
    apply_platform(args.platform)

    import jax

    from ddlbench_tpu.distributed import (backend_provenance,
                                          enable_compilation_cache,
                                          warn_cpu_fallback)

    enable_compilation_cache()
    prov = backend_provenance(args.platform)
    warn_cpu_fallback(prov, "servechaos")

    from ddlbench_tpu.config import DATASETS, ServeConfig
    from ddlbench_tpu.models import init_model
    from ddlbench_tpu.models.zoo import get_model
    from ddlbench_tpu.serve.engine import make_server, supports_serve
    from ddlbench_tpu.serve.workload import make_workload
    from ddlbench_tpu.telemetry.stats import serve_summary

    spec = DATASETS[args.benchmark]
    if spec.kind != "tokens":
        p.error(f"-b {args.benchmark!r} is not a causal-LM token workload")
    model = get_model(args.model, spec)
    if not supports_serve(model):
        p.error(f"{args.model} has layers without serving support")
    params, state, _ = init_model(model, jax.random.key(0))

    plo, ptyp, phi = (int(x) for x in args.prompt_lens.split(","))
    olo, otyp, ohi = (int(x) for x in args.out_lens.split(","))
    groups = prefix_len = 0
    if args.shared_prefix:
        try:
            groups, prefix_len = (int(x)
                                  for x in args.shared_prefix.split(":"))
        except ValueError:
            p.error("--shared-prefix wants G:P (groups:prefix_tokens), "
                    f"got {args.shared_prefix!r}")
    # --corrupt arms the checksum ledger unless --no-detect asks for the
    # honest no-defense run; the scrubber defaults to a full pool sweep
    # per step so a settled-page flip is always caught within one step
    # (--scrub N trades detection latency for the verify budget)
    detect = bool(corrupts) and not args.no_detect
    scrub = (0 if not detect else
             (args.scrub if args.scrub is not None else args.pool_pages))
    cfg = ServeConfig(
        max_batch=args.max_batch, pool_pages=args.pool_pages,
        page=args.page, max_len=min(args.max_len, spec.seq_len),
        token_budget=args.token_budget,
        prefill_chunk=(args.page if args.prefill_chunk is None
                       else args.prefill_chunk),
        replicas=1 if disagg else args.replicas, slo_ttft=args.slo_ttft,
        slo_itl=args.slo_itl, heartbeat=args.heartbeat,
        kv_dtype=args.kv_dtype or "float32",
        prefix_cache=args.prefix_cache,
        integrity=detect, scrub=scrub,
        speculative=args.speculative or "none")
    cfg.validate()

    def workload():
        # fresh per run: closed-loop drivers stamp arrivals/deadlines
        return make_workload(
            seed=args.seed, n_requests=args.requests,
            vocab=spec.num_classes, arrival=args.arrival, rate=args.rate,
            burst_size=args.burst_size, burst_factor=args.burst_factor,
            prompt_lo=plo, prompt_typical=ptyp, prompt_hi=phi,
            out_lo=olo, out_typical=otyp, out_hi=ohi,
            tail_frac=args.tail_frac, prefix_groups=groups,
            prefix_len=prefix_len, max_len=cfg.max_len,
            deadline_slack=args.deadline_slack,
            batch_frac=args.tier_mix or 0.0)

    def build(shared):
        if disagg:
            from ddlbench_tpu.serve.handoff import make_disaggregated

            return make_disaggregated(model, params, state, cfg,
                                      disagg[0], disagg[1],
                                      shared_fns=shared)
        return make_server(model, params, state, cfg, shared_fns=shared)

    def check_layers(srv):
        # an explicit @L pin must name a layer that owns a KV pool —
        # checked on the first built server, before any run burns steps
        if not corrupts:
            return
        from ddlbench_tpu.serve.integrity import pool_layers

        valid = pool_layers(srv.engines[0])
        for t, fleet, r, tgt, layer, slot in corrupts:
            if layer is not None and layer not in valid:
                p.error(f"--corrupt @{layer}.{slot}: model layer {layer} "
                        f"owns no KV pool (attention layers: {valid})")

    t0 = time.perf_counter()
    # -- control: the same workload, no faults — the bitwise stream
    # reference and the unfaulted goodput baseline (skippable)
    control = None
    shared_fns = None
    if not args.no_control:
        control = build(None)
        check_layers(control)
        shared_fns = control.engines[0].jit_fns()
        _run(control, workload(), args, retry)
    # -- scripted-recovery baseline (--autoscale only): the SAME faults
    # with NO controller — the PR 15 behavior where a killed replica
    # stays dead — so the headline "auto-repair MTTR <= scripted MTTR"
    # is measured in-run, against the identical workload and compiles
    scripted_mttrs = None
    if autoscale and kills:
        if scripted_ok:
            baseline = build(shared_fns)
            shared_fns = baseline.engines[0].jit_fns()
            _run(baseline, workload(), args, retry,
                 events=_fault_events(kills, stalls))
            scripted_mttrs = mttr_from_events(baseline.fail_events,
                                              baseline.finished)
        else:
            print("servechaos: NOTE kill schedule needs the controller's "
                  "repairs to stay feasible; skipping the scripted-"
                  "recovery baseline (mttr_scripted_* reported as null)",
                  file=sys.stderr, flush=True)
    # -- the chaos run
    server = build(shared_fns)
    if args.no_control:
        check_layers(server)
    controllers = None
    if autoscale:
        from ddlbench_tpu.serve.autoscaler import (AutoscalePolicy,
                                                   make_controllers,
                                                   replica_hours)

        pol = AutoscalePolicy(lo=autoscale[0], hi=autoscale[1],
                              window=args.scale_window,
                              cooldown_up=args.scale_cooldown,
                              cooldown_down=args.scale_cooldown)
        controllers = make_controllers(server, pol)
    dstats = {}
    corrupts_fired = []
    duration = _run(server, workload(), args, retry,
                    events=sorted(
                        _fault_events(kills, stalls)
                        + _corrupt_events(corrupts, corrupts_fired),
                        key=lambda e: e[0]),
                    driver_stats=dstats, controllers=controllers)
    wall = time.perf_counter() - t0

    fin = server.finished
    eng_stats = server.stats_summary()
    summary = serve_summary(fin, duration=duration, slo_ttft=args.slo_ttft,
                            slo_itl=args.slo_itl,
                            per_tier=args.tier_mix is not None)
    from ddlbench_tpu.tools.servebench import _round6, shed_accounting

    acct = shed_accounting(args.requests, len(fin),
                           int(eng_stats["shed"]),
                           int(eng_stats["timeouts"]), dstats)
    mttrs = mttr_from_events(server.fail_events, fin)
    mttr_ok = [m for m in mttrs if m is not None]
    # the headline repair verdict: mean auto-repair MTTR vs the
    # scripted-recovery baseline's (None when either side has no sample)
    scripted_ok_mttrs = [m for m in (scripted_mttrs or []) if m is not None]
    repair_le_scripted = None
    if mttr_ok and scripted_ok_mttrs:
        repair_le_scripted = (sum(mttr_ok) / len(mttr_ok)
                              <= sum(scripted_ok_mttrs)
                              / len(scripted_ok_mttrs))
    # bitwise failover gate: every rid completed in BOTH runs must carry
    # the identical token stream; the compared set is the intersection
    # (deadline runs can legitimately time out different rids per run)
    streams_match = None
    streams_compared = streams_diverged = 0
    if control is not None:
        ctrl_fin = {f["rid"]: f["tokens"] for f in control.finished}
        run_fin = {f["rid"]: f["tokens"] for f in fin}
        both = sorted(set(ctrl_fin) & set(run_fin))
        streams_compared = len(both)
        streams_diverged = sum(1 for rid in both
                               if ctrl_fin[rid] != run_fin[rid])
        streams_match = streams_diverged == 0

    rec = {
        "tool": "servechaos",
        "model": args.model,
        "benchmark": args.benchmark,
        "arrival": args.arrival,
        "rate": args.rate if args.arrival != "closed" else None,
        "concurrency": (args.concurrency if args.arrival == "closed"
                        else None),
        "requests": args.requests,
        "seed": args.seed,
        "replicas": args.replicas,
        **({"disaggregate": args.disaggregate,
            "prefill_replicas": disagg[0],
            "decode_replicas": disagg[1]} if disagg else {}),
        "max_batch": cfg.max_batch,
        "pool_pages": cfg.pool_pages,
        "page": cfg.page,
        "max_len": cfg.max_len,
        "time_unit": "model_pass",
        # the injection schedule as given + what actually happened
        "kill": args.kill,
        "stall": args.stall,
        "heartbeat": args.heartbeat,
        "deadline_slack": args.deadline_slack,
        "retry": args.retry,
        "tier_mix": args.tier_mix,
        "kv_dtype": cfg.kv_dtype,
        "speculative": cfg.speculative,
        # --prefix-cache only (plain rows keep their key set): the cache
        # the `prefix` corrupt target flips shared pages in, plus the
        # shared-prefix traffic shape that makes those pages shared
        **({"prefix_cache": True,
            "shared_prefix": args.shared_prefix}
           if args.prefix_cache else {}),
        "kills_fired": len(server.fail_events),
        "stalls_fired": len(server.stall_events),
        "heartbeat_drains": len(server.heartbeat_events),
        "fail_events": server.fail_events,
        "heartbeat_events": [
            {k: (round(v, 6) if isinstance(v, float) else v)
             for k, v in e.items()} for e in server.heartbeat_events],
        # recovery: virtual time from each kill to the last displaced
        # request's first post-failover token
        "mttr_replica_s": [m if m is None else round(m, 6) for m in mttrs],
        "mttr_replica_s_mean": (round(sum(mttr_ok) / len(mttr_ok), 6)
                                if mttr_ok else None),
        "mttr_replica_s_max": (round(max(mttr_ok), 6) if mttr_ok else None),
        # terminal-state accounting (the no-loss gate) — ONE formula
        # shared with servebench (shed_accounting)
        **acct,
        "timeouts": int(eng_stats["timeouts"]),
        "shed": int(eng_stats["shed"]),
        # bitwise failover gate vs the unfaulted control
        "streams_match": streams_match,
        "streams_compared": streams_compared,
        "streams_diverged": streams_diverged,
        "control_completed": (len(control.finished)
                              if control is not None else None),
        "final_replicas": len(server.engines),
        # --autoscale only: the controller's repair ledger + economics,
        # the scripted-recovery baseline MTTRs (PR 15 behavior, same
        # faults, no controller), and the repair-vs-scripted verdict
        **({"autoscale": args.autoscale,
            "scale_window": args.scale_window,
            "scale_cooldown": args.scale_cooldown,
            "repairs": sum(c.repairs for c in controllers),
            "scale_events": sum(c.scale_events for c in controllers),
            "replica_hours": round(replica_hours(controllers), 6),
            "autoscale_events": _round6(
                [e for c in controllers for e in c.events]),
            "mttr_scripted_s": (None if scripted_mttrs is None else
                                [m if m is None else round(m, 6)
                                 for m in scripted_mttrs]),
            "mttr_scripted_s_mean": (round(sum(scripted_ok_mttrs)
                                           / len(scripted_ok_mttrs), 6)
                                     if scripted_ok_mttrs else None),
            "mttr_scripted_s_max": (round(max(scripted_ok_mttrs), 6)
                                    if scripted_ok_mttrs else None),
            "repair_mttr_le_scripted": repair_le_scripted}
           if autoscale else {}),
        **{k: (round(v, 6) if isinstance(v, float) else v)
           for k, v in summary.items()},
        # completed comes from serve_summary; timeouts/shed are already
        # in the row as exact ints (the spread would re-insert floats)
        **{k: (round(v, 6) if isinstance(v, float) else v)
           for k, v in eng_stats.items()
           if k not in ("completed", "timeouts", "shed")},
        **_sdc_block(args, corrupts, corrupts_fired, detect, cfg, server,
                     fin, control, streams_diverged, acct),
        **prov,
    }
    if args.wall_clock:
        rec["wall_s"] = round(wall, 3)
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
