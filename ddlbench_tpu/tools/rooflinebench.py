"""Per-op HBM-traffic table of the compiled train step (roofline evidence).

PERF.md's roofline argument — "the ResNet-50 step is HBM-bound at ~94% of
peak, going faster requires changing benchmark semantics" — was asserted
from the AGGREGATE XLA cost analysis (VERDICT r3 weak #1: "asserted, not
proven"). This tool opens the box: it AOT-compiles the real train step,
walks the post-optimization HLO of the executable, prices every instruction
(operand + result bytes, free ops excluded), and emits

* a category table (convolution / reduce / elementwise-fusion / copy /
  optimizer / other) with bytes per step and share of total,
* the top-N single instructions by bytes with shapes and source op names,
* the aggregate vs ``cost_analysis()`` cross-check,
* an analytic irreducibility model: conv I/O + BN's extra activation
  passes + parameter/optimizer traffic, so "what a fused-BN kernel could
  save" is a number, not a claim.

The table must come from the TPU executable (CPU fusion decisions differ):
run it inside a tunnel window (scripts/tpu_round4.sh queues it).

Usage:
    python -m ddlbench_tpu.tools.rooflinebench [--arch resnet50]
        [--benchmark imagenet] [--batch-size 256] [--top 25] [--platform cpu]
"""

from __future__ import annotations

import argparse
import collections
import json
import re
import sys

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
_SHAPE_RE = re.compile(r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([\d,]*)\]")
# opcodes that move no HBM bytes of their own (matched on the opcode token,
# not by substring — an instruction whose OPERAND is named %constant.7 is
# not free)
_FREE_OPS = {"parameter", "constant", "get-tuple-element", "tuple",
             "bitcast", "after-all", "partition-id", "replica-id",
             "iota"}


def shape_bytes(text: str) -> int:
    """Total bytes of every dtype[dims] shape literal in ``text`` (tuples
    sum their elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def categorize(opcode: str, rhs: str) -> str:
    """Category from the instruction's OPCODE; fusions/custom-calls refine
    via their metadata op_name (operand names like %convolution.5 inside the
    argument list must not leak into the category — they belong to the
    producer's row)."""
    if opcode == "convolution":
        return "convolution"
    if opcode == "dot":
        return "matmul"
    if opcode in ("all-reduce", "reduce-scatter", "all-gather",
                  "collective-permute", "all-to-all"):
        return "collective"
    if opcode in ("reduce", "reduce-window"):
        return "reduce"
    if opcode in ("copy", "transpose", "reshape", "copy-start", "copy-done"):
        return "copy/transpose"
    if opcode in ("scatter", "gather", "dynamic-slice",
                  "dynamic-update-slice"):
        return "gather/scatter"
    if opcode in ("fusion", "custom-call"):
        meta = re.search(r'op_name="([^"]*)"', rhs)
        tgt = re.search(r'custom_call_target="([^"]*)"', rhs)
        hint = ((meta.group(1) if meta else "")
                + " " + (tgt.group(1) if tgt else "")).lower()
        if "conv" in hint:
            return "convolution"
        if ("dot" in hint or "matmul" in hint or "einsum" in hint
                or "gemm" in hint):
            return "matmul"
        if "reduce" in hint or "norm" in hint or "mean" in hint:
            return "reduce"
        if "scatter" in hint or "gather" in hint or "slice" in hint:
            return "gather/scatter"
        if "transpose" in hint:
            return "copy/transpose"
        return ("elementwise-fusion" if opcode == "fusion"
                else "custom-call")
    return "other"


def per_op_table(hlo_text: str):
    """[(name, category, bytes, result_shape, op_name_meta)] for the entry
    computation of a post-optimization HLO dump."""
    entry = None
    for m in re.finditer(r"^ENTRY [^{]*\{(.*?)^\}", hlo_text,
                         re.S | re.M):
        entry = m.group(1)
    if entry is None:
        raise ValueError("no ENTRY computation in HLO text")

    sizes: dict[str, int] = {}
    rows = []
    for line in entry.splitlines():
        line = line.strip()
        m = re.match(r"(?:ROOT )?%([\w.\-]+) = (.*)", line)
        if not m:
            continue
        name, rhs = m.groups()
        # result shape = shapes before the op call opens; operands resolved
        # by name lookup (calls=/to_apply= computations are not operands)
        call = re.search(r"\b([a-z][\w\-]*)\(", rhs)
        result_text = rhs[: call.start()] if call else rhs
        opcode = call.group(1) if call else ""
        result_b = shape_bytes(result_text)
        sizes[name] = result_b
        if opcode in _FREE_OPS:
            continue
        operand_b = sum(
            sizes.get(op, 0)
            for op in dict.fromkeys(re.findall(r"%([\w.\-]+)", rhs))
            if op != name)
        meta = re.search(r'op_name="([^"]*)"', rhs)
        shape_m = _SHAPE_RE.search(result_text)
        rows.append({
            "name": name,
            "category": categorize(opcode, rhs),
            "bytes": result_b + operand_b,
            "result_shape": shape_m.group(0) if shape_m else "?",
            "op_name": meta.group(1) if meta else "",
        })
    return rows


def analytic_model(model, cfg, batch: int) -> dict:
    """Semantic lower bound on activation traffic, per step, in bytes.

    Counts for each conv/BN block (bf16 activations, f32 stats):
      conv fwd: read in + read kernel + write out;
      BN fwd: stats read of out + normalize read/write  -> 2 extra passes;
      bwd: ~2x fwd activation traffic (textbook, matches the measured
      fwd vs fwd+bwd split in PERF.md);
      params: grads + momentum + update = 5 f32 passes over param bytes.
    A conv-epilogue-stats kernel can remove ONE of BN's two extra output
    passes per block; the normalize pass itself is not removable without
    changing torch-BN semantics (the stats must be complete before any
    output element is normalized).
    """
    import math

    import jax

    from ddlbench_tpu.models import init_model

    # shapes suffice — eval_shape skips the real (threefry-heavy) init, so
    # the analytic bound is computable in milliseconds on any host. The
    # per-layer boundary shapes are Python int tuples computed during
    # tracing; eval_shape would abstract them in the RETURN value, so they
    # are captured from inside the traced function instead.
    captured = {}

    def _init(k):
        p, s, shp = init_model(model, k)
        captured["shapes"] = shp
        return p, s

    params, states = jax.eval_shape(_init, jax.random.key(0))
    shapes = captured["shapes"]
    act = 2  # bf16
    conv_io = bn_extra = 0
    for p, s, in_shape, out_shape in zip(params, states, shapes, shapes[1:]):
        # only layers that actually carry a conv (a 4-D kernel leaf) and a
        # BN (running-stats state, models/layers.bn_init) contribute —
        # pool/flatten/fc layers move bytes too, but charging them conv+BN
        # traffic inflated the "irreducible" bound (ADVICE r4)
        has_conv = any(getattr(x, "ndim", 0) == 4 for x in jax.tree.leaves(p))
        has_bn = bool(jax.tree.leaves(s))
        in_n = math.prod(in_shape) if in_shape else 0
        out_n = math.prod(out_shape) if out_shape else 0
        if has_conv:
            conv_io += batch * (in_n + out_n) * act
        if has_bn:
            bn_extra += batch * 2 * out_n * act
    param_b = sum(int(x.size) * 4 for x in jax.tree.leaves(params))
    fwd = conv_io + bn_extra
    return {
        "fwd_conv_io_gb": conv_io / 1e9,
        "fwd_bn_extra_passes_gb": bn_extra / 1e9,
        "bwd_approx_gb": 2 * fwd / 1e9,
        "param_opt_traffic_gb": 5 * param_b / 1e9,
        "analytic_total_gb": (3 * fwd + 5 * param_b) / 1e9,
        "epilogue_stats_savable_gb": bn_extra / 2 / 1e9,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--arch", default="resnet50")
    p.add_argument("--benchmark", default="imagenet")
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--top", type=int, default=25)
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.config import RunConfig
    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.distributed import enable_compilation_cache
    from ddlbench_tpu.models import get_model
    from ddlbench_tpu.parallel.api import make_strategy

    enable_compilation_cache()
    cfg = RunConfig(benchmark=args.benchmark, strategy="single",
                    arch=args.arch, batch_size=args.batch_size,
                    compute_dtype=args.dtype, steps_per_epoch=4)
    strategy = make_strategy(cfg)
    data = make_synthetic(cfg.dataset(), args.batch_size, steps_per_epoch=4)
    ts = strategy.init(jax.random.key(cfg.seed))
    x, y = data.batch(0, 0)
    compiled = strategy.train_step.lower(
        ts, x, y, jnp.float32(cfg.resolved_lr())).compile()

    rows = per_op_table(compiled.as_text())
    rows.sort(key=lambda r: -r["bytes"])
    cats = collections.Counter()
    for r in rows:
        cats[r["category"]] += r["bytes"]
    total = sum(cats.values())

    cost = {}
    try:
        c = compiled.cost_analysis()
        if isinstance(c, list):
            c = c[0]
        cost = {"flops": c.get("flops", 0.0),
                "bytes_accessed": c.get("bytes accessed", 0.0)}
    except Exception:
        pass

    doc = {
        "arch": args.arch,
        "benchmark": args.benchmark,
        "batch_size": args.batch_size,
        "dtype": args.dtype,
        "platform": jax.devices()[0].platform,
        "num_ops": len(rows),
        "total_op_bytes_gb": total / 1e9,
        "cost_analysis": cost,
        "categories_gb": {k: round(v / 1e9, 3)
                          for k, v in cats.most_common()},
        "categories_pct": {k: round(100.0 * v / max(1, total), 1)
                           for k, v in cats.most_common()},
        "top_ops": [
            {**r, "gb": round(r["bytes"] / 1e9, 3)}
            for r in rows[: args.top]
        ],
        "analytic_model": analytic_model(
            get_model(args.arch, args.benchmark), cfg, args.batch_size),
    }
    for r in doc["top_ops"]:
        del r["bytes"]
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
