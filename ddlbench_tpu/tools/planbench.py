"""Planner-quality harness: `--plan auto` predictions vs measured reality.

For each (model, world) pair this tool runs the whole `--plan auto` loop —
profile the model, solve the dp/pp/tp mix + stage split + schedule
(partition/planner.py), rewrite the config onto the winning engines — then
EXECUTES the winner and times real steps, printing one JSON row per point:

    {"arch": "resnet18", "benchmark": "cifar10", "world": 4,
     "pp": 2, "dp": 2, "tp": 1, "schedule": "1f1b", "bounds": [0, 5, 9],
     "predicted_ms": N, "measured_ms": N, "err_frac": N,
     "peak_bytes_per_chip": N, "candidates": N, "feasible": N}

``err_frac = (measured - predicted) / measured`` is the planner's
prediction error — the number that makes planner quality a reported figure
instead of a claim. On the CPU mesh the ABSOLUTE error is expected to be
large with ``--profile-mode flops`` (the cost model prices a TPU v5e); use
``--profile-mode time`` (the default here) so per-layer costs are measured
on the machine that executes them and the error mostly reflects the
schedule/communication model. The on-chip rows land via
scripts/tpu_round17.sh.

Usage:
    python -m ddlbench_tpu.tools.planbench \
        [--pairs lenet:mnist,resnet18:cifar10,transformer_s:synthtext] \
        [--worlds 2,4] [--micro-batch 4] [--num-microbatches 8] \
        [--steps 8] [--warmup 2] [--profile-mode time] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys

DEFAULT_PAIRS = "lenet:mnist,resnet18:cifar10,transformer_s:synthtext"


def bench_pair(arch: str, benchmark: str, world: int, args,
               audit_manifests=None) -> dict:
    """One (model, world) row: solve, execute, compare."""
    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.config import RunConfig
    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.parallel.api import make_strategy
    from ddlbench_tpu.partition.planner import (_apply_rewrite,
                                                plan_for_config)
    from ddlbench_tpu.tools.timing import timed_steps

    cfg0 = RunConfig(
        benchmark=benchmark, strategy="gpipe", arch=arch,
        num_devices=world, plan="auto", profile_mode=args.profile_mode,
        micro_batch_size=args.micro_batch,
        num_microbatches=args.num_microbatches,
        compute_dtype=args.dtype, steps_per_epoch=args.steps)
    plan, rewrite, _ = plan_for_config(cfg0)
    w = plan.winner
    cfg = _apply_rewrite(cfg0, rewrite)
    row = {
        "arch": arch, "benchmark": benchmark, "world": world,
        "pp": w.pp, "dp": w.dp, "tp": w.tp, "schedule": w.schedule,
        "bounds": list(w.bounds) if w.bounds else None,
        "strategy": cfg.strategy,
        "predicted_ms": round(w.step_time_ms, 4),
        "peak_bytes_per_chip": round(w.peak_bytes_per_chip, 1),
        "candidates": len(plan.candidates),
        "feasible": sum(1 for c in plan.candidates if c.feasible),
    }
    strategy = make_strategy(cfg)
    data = make_synthetic(cfg.dataset(), cfg.global_batch(),
                          steps_per_epoch=args.steps)
    ts = strategy.init(jax.random.key(cfg.seed))
    lr = jnp.float32(cfg.resolved_lr())

    def run_step(x, y):
        nonlocal ts
        ts, m = strategy.train_step(ts, *strategy.shard_batch(x, y), lr)
        return m

    dt = timed_steps(run_step, data.batch, args.steps, args.warmup)
    measured = 1000.0 * dt / args.steps
    row["measured_ms"] = round(measured, 4)
    row["err_frac"] = round((measured - w.step_time_ms) / measured, 4) \
        if measured > 0 else None
    if audit_manifests is not None:
        # compiled-program audit for the winner: manifest + comm_stats
        # tie-out, plus the planner's per-stage HBM-model signed error vs
        # memory_analysis() (recorded into partition.json when the run
        # has a persisted plan — here it rides the row)
        from ddlbench_tpu.telemetry.audit import (planner_stage_hbm_audit,
                                                  lower_manifest,
                                                  reconcile_train,
                                                  record_hbm_audit)

        x0, y0 = data.batch(0, 0)
        # some engines wrap their jit in a telemetry-span function; lower
        # the underlying executable either way (bench.py idiom)
        jit_step = getattr(strategy, "_jit_train_step", None) \
            or strategy.train_step
        man = lower_manifest(
            jit_step, (ts, *strategy.shard_batch(x0, y0), lr),
            f"plan/{arch}:{benchmark}@{world}",
            mesh=getattr(strategy, "mesh", None))
        man["reconcile"] = reconcile_train(strategy, man)
        hbm = planner_stage_hbm_audit(w.as_record(), man, world)
        man["hbm_audit"] = hbm
        audit_manifests.append(man)
        if hbm is not None:
            row["hbm_err_frac_per_stage"] = [
                round(s["err_frac"], 4) if s["err_frac"] is not None
                else None for s in hbm["stages"]]
            record_hbm_audit(cfg, hbm)
    return row


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--pairs", default=DEFAULT_PAIRS,
                   help="comma list of arch:benchmark pairs to sweep")
    p.add_argument("--worlds", default="2,4",
                   help="comma list of chip counts per pair")
    p.add_argument("--micro-batch", type=int, default=4,
                   help="pre-plan micro-batch (the gpipe batch grammar the "
                        "plan preserves: global = micro x microbatches)")
    p.add_argument("--num-microbatches", type=int, default=8)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--profile-mode", default="time",
                   choices=("flops", "time"),
                   help="time (default) measures per-layer costs on THIS "
                        "machine, so err_frac reflects the schedule model "
                        "rather than the TPU constants; flops is the "
                        "deterministic device-free mode")
    p.add_argument("--dtype", default="float32")
    p.add_argument("--audit", default=None, metavar="PATH",
                   help="also emit the winner's compiled-program audit "
                        "manifest per point (telemetry/audit.py) — "
                        "includes the planner's per-stage HBM error vs "
                        "memory_analysis() — into one ledger JSON")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax

    from ddlbench_tpu.distributed import record_provenance

    prov = record_provenance(args.platform, "planbench")
    print(json.dumps({"provenance": {**prov,
                                     "platform_arg": args.platform}}),
          flush=True)
    avail = len(jax.devices())
    rows = []
    audit_manifests = [] if args.audit else None
    for pair in args.pairs.split(","):
        arch, benchmark = pair.strip().split(":")
        for world in (int(v) for v in args.worlds.split(",")):
            if world > avail:
                print(json.dumps({"arch": arch, "world": world, "error":
                                  f"{world} devices exceed the {avail} "
                                  f"attached"}), flush=True)
                continue
            try:
                row = bench_pair(arch, benchmark, world, args,
                                 audit_manifests)
            except ValueError as e:  # e.g. branchy arch, no feasible mix
                row = {"arch": arch, "benchmark": benchmark,
                       "world": world, "error": str(e)}
            row = {**row, "schema_version": prov["schema_version"],
                   "jax_backend": prov["jax_backend"],
                   "cpu_fallback": prov["cpu_fallback"]}
            print(json.dumps(row), flush=True)
            rows.append(row)
    if args.audit:
        from ddlbench_tpu.telemetry.audit import write_manifests

        write_manifests(args.audit, audit_manifests,
                        header={**prov, "tool": "planbench"})
        print(json.dumps({"audit": args.audit,
                          "programs": len(audit_manifests)}), flush=True)
    good = [r for r in rows if "err_frac" in r and r["err_frac"] is not None]
    if good:
        errs = sorted(abs(r["err_frac"]) for r in good)
        print(json.dumps({
            "summary": {
                "points": len(good),
                "abs_err_frac_p50": round(errs[len(errs) // 2], 4),
                "abs_err_frac_max": round(errs[-1], 4),
            }}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
