"""Graph-file utilities: the working analog of the reference's aux optimizer
scripts (pipedream-fork/optimizer/scripts/compress_graph_branches.py and
convert_profiles_to_graphs.py, SURVEY.md §2 C6 — both hardcode input paths;
this is the same capability as a real CLI).

    python -m ddlbench_tpu.tools.graphtool compress graph.txt out_dir
    python -m ddlbench_tpu.tools.graphtool from-csv profile.csv out_dir
    python -m ddlbench_tpu.tools.graphtool dot graph.txt out_dir

Each subcommand writes ``graph.txt`` (reference-format text) and ``graph.dot``
into ``out_dir``. ``compress`` merges linear branch bodies
(Graph.compress_branches) and verifies aggregate fidelity; ``from-csv``
imports a per-layer profile CSV (Graph.from_profile_csv).
"""

from __future__ import annotations

import argparse
import os
import sys

from ddlbench_tpu.graph.graph import Graph


def _emit(g: Graph, out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "graph.txt"), "w") as f:
        f.write(str(g))
    g.to_dot(os.path.join(out_dir, "graph.dot"))
    print(f"wrote {out_dir}/graph.txt ({len(g.nodes)} nodes) and graph.dot")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="graphtool", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("compress", "from-csv", "dot"):
        sp = sub.add_parser(name)
        sp.add_argument("input")
        sp.add_argument("out_dir")
    args = p.parse_args(argv)

    if args.cmd == "from-csv":
        g = Graph.from_profile_csv(args.input)
    else:
        with open(args.input) as f:
            g = Graph.from_str(f.read())
    if args.cmd == "compress":
        c = g.compress_branches()
        g.check_fidelity(c)
        print(f"compressed {len(g.nodes)} -> {len(c.nodes)} nodes "
              f"(aggregate cost preserved)")
        g = c
    _emit(g, args.out_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
