"""Translation-accuracy benchmark: seq2seq trains to SEQUENCE accuracy.

The reference's translation protocol is anchored on output quality (GNMT
trains to a BLEU target; pipedream-fork/runtime/translation scrapes loss +
BLEU-oriented eval — SURVEY.md §2 C13). The image-side analog here is the
digits accuracy-parity gate (tools/accparity.py); this is the seq2seq side:
a DETERMINISTIC synthetic language — target = token-permuted source in
REVERSED order — that a correct encoder-decoder must learn essentially
perfectly (the reversal forces genuine cross-position attention; the
permutation forces the full vocabulary mapping), measured by exact-match
sequence accuracy on held-out sources.

Beyond training correctness this validates INFERENCE quality end to end on
TRAINED weights — the place where cache/mask/position bugs that random-
weight token-identity tests can miss actually bite: greedy, beam, the
full-forward reference loop, and the paged copy-on-write beam path must all
reproduce the learned mapping.

One JSON document:
    {"seq_accuracy": {"greedy": 1.0, "beam": 1.0, "paged_beam": 1.0, ...},
     "token_accuracy": ..., "pass": true}

The deterministic task is a GATE, not a graded quality benchmark: exact
match is 100% reachable, so it catches outright decode breakage only
(VERDICT r4 weak #5). ``--noise e`` adds the graded variant: each source
token is independently corrupted to a uniform random token with probability
e AFTER the clean target is formed (a noisy channel), so the best possible
per-token accuracy is the Bayes ceiling (1-e) + e/(V-4) < 1 — the measured
token accuracy then sits strictly below 100% with headroom to move, and the
gate becomes "within --noise-margin of the ceiling".

Usage:
    python -m ddlbench_tpu.tools.mtacc [--steps 400] [--src-len 12]
        [--vocab 64] [--batch 64] [--threshold 0.95] [--noise 0.1]
        [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--src-len", type=int, default=12)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--eval-size", type=int, default=64)
    p.add_argument("--beam", type=int, default=4)
    p.add_argument("--threshold", type=float, default=0.95,
                   help="minimum held-out exact-match sequence accuracy "
                        "(noise == 0)")
    p.add_argument("--noise", type=float, default=0.0,
                   help="source-corruption probability: > 0 switches to the "
                        "graded noisy-channel variant gated on token "
                        "accuracy vs the Bayes ceiling")
    p.add_argument("--noise-margin", type=float, default=0.05,
                   help="allowed gap below the Bayes token-accuracy ceiling "
                        "(noise > 0)")
    p.add_argument("--arch", default="seq2seq_t")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import numpy as np
    import jax
    import jax.numpy as jnp

    import ddlbench_tpu.models.decode as dec
    import ddlbench_tpu.models.seq2seq as s2s
    from ddlbench_tpu.config import RunConfig
    from ddlbench_tpu.data.synthetic import mask_source_labels
    from ddlbench_tpu.distributed import enable_compilation_cache
    from ddlbench_tpu.parallel.single import SingleStrategy

    enable_compilation_cache()
    s2s._VARIANTS.setdefault("seq2seq_t",
                             dict(d_model=32, n_layers=2, n_heads=4))
    V, S = args.vocab, args.src_len
    T = 2 * S + 2  # src S | BOS | tgt S | EOS
    BOS, EOS = 1, 2  # ids 0..3 reserved (pad/bos/eos/unk convention)
    rng = np.random.default_rng(0)
    perm = rng.permutation(np.arange(4, V))

    def make(n, seed):
        r = np.random.default_rng(seed)
        src = r.integers(4, V, (n, S))
        tgt = perm[src - 4][:, ::-1]  # target formed from the CLEAN source
        if args.noise > 0.0:  # then the channel corrupts what the model sees
            corrupt = r.random((n, S)) < args.noise
            src = np.where(corrupt, r.integers(4, V, (n, S)), src)
        rows = np.zeros((n, T + 1), np.int32)
        rows[:, :S] = src
        rows[:, S] = BOS
        rows[:, S + 1:S + 1 + S] = tgt
        rows[:, S + 1 + S] = EOS
        return rows

    model = s2s.build_seq2seq(args.arch, (T,), V, S)
    cfg = RunConfig(benchmark="synthmt", strategy="single", arch=args.arch,
                    batch_size=args.batch, compute_dtype="float32",
                    optimizer="adam", label_smoothing=0.0)
    if args.steps < 1:
        p.error("--steps must be >= 1 (the gate measures TRAINED accuracy)")
    strat = SingleStrategy(model, cfg)
    ts = strat.init(jax.random.key(0))
    lr = jnp.float32(args.lr)
    for step in range(args.steps):
        rows = jnp.asarray(make(args.batch, 10_000 + step))
        x, lab = rows[:, :-1], rows[:, 1:]
        lab = mask_source_labels(lab, S)
        ts, m = strat.train_step(ts, x, lab, lr)
    final_loss = float(m["loss"])

    # held-out evaluation (seed range disjoint from training)
    test = make(args.eval_size, 7)
    src = jnp.asarray(test[:, :S])
    gold = test[:, S + 1:S + 1 + S]
    params, state = ts.params, ts.model_state

    def accuracy(decoded) -> tuple:
        pred = np.asarray(decoded)[:, S + 1:S + 1 + S]
        return (float((pred == gold).all(1).mean()),
                float((pred == gold).mean()))

    outs = {
        "greedy": dec.greedy_decode(model, params, state, src, T),
        "beam": dec.beam_search_decode(model, params, state, src, T,
                                       beam=args.beam)[0],
        "paged_beam": dec.beam_search_decode(model, params, state, src, T,
                                             beam=args.beam, paged=True)[0],
        "full_forward_greedy": s2s.greedy_decode(model, params, state, src,
                                                 T, use_cache=False),
    }
    seq_acc, tok_acc = {}, {}
    for name, out in outs.items():
        seq_acc[name], tok_acc[name] = accuracy(out)

    doc = {
        "tool": "mtacc",
        "arch": args.arch,
        "train_steps": args.steps,
        "final_loss": round(final_loss, 5),
        "eval_size": args.eval_size,
        "seq_accuracy": seq_acc,
        "token_accuracy": tok_acc,
        "platform": jax.devices()[0].platform,
    }
    if args.noise > 0.0:
        # Bayes ceiling: a corrupted position (prob e) is unrecoverable —
        # the best predictor maps the OBSERVED token, right with prob
        # 1/(V-4) there — so max E[token acc] = (1-e) + e/(V-4). Gate each
        # decode path's token accuracy within --noise-margin of it.
        ceiling = (1.0 - args.noise) + args.noise / (V - 4)
        ok = all(v >= ceiling - args.noise_margin for v in tok_acc.values())
        doc.update({
            "task": f"noisy-channel variant: source corrupted with prob "
                    f"{args.noise} after the clean target is formed "
                    f"(S={S}, vocab={V}) — graded quality metric with "
                    f"headroom, not a 100%-reachable gate",
            "noise": args.noise,
            "token_ceiling": round(ceiling, 5),
            "noise_margin": args.noise_margin,
            "pass": ok,
        })
    else:
        ok = all(v >= args.threshold for v in seq_acc.values())
        doc.update({
            "task": f"target = vocabulary-permuted source, reversed "
                    f"(S={S}, vocab={V}; deterministic — exact match is the "
                    f"correctness bar)",
            "threshold": args.threshold,
            "pass": ok,
        })
    print(json.dumps(doc))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
