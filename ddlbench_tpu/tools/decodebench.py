"""Inference (decode) throughput microbenchmark.

GNMT-analog inference measurement (the reference benchmarks only training;
its translation runtime ships beam-search inference without a throughput
harness — SURVEY.md §2 C13). Measures tokens/sec for greedy and beam decode
on a seq2seq model, KV-cached (models/decode.py) vs the full-forward
reference path, printing one JSON line per configuration:

    {"tool": "decodebench", "mode": "greedy", "cached": true,
     "tokens_per_sec": N, "ms_per_token": M, ...}

Usage:
    python -m ddlbench_tpu.tools.decodebench [-m seq2seq_s] [-b synthmt]
        [--batch 8] [--beam 4] [--repeats 3] [--skip-uncached] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _bench(fn, sync, repeats: int):
    fn()  # compile
    sync()
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        sync()
        best = min(best, time.perf_counter() - t0)
    return best


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-m", "--model", default="seq2seq_s")
    p.add_argument("-b", "--benchmark", default="synthmt")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--beam", type=int, default=4)
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--total-len", type=int, default=None,
                   help="decode out to this stream length (default: the "
                        "benchmark's full seq_len; lower it for long-context "
                        "specs where the compile of thousands of decode "
                        "steps would dominate)")
    p.add_argument("--cache-dtype", default="float32",
                   help="KV-cache storage dtype for the paged variants "
                        "(bfloat16 halves cache traffic; scores stay f32)")
    p.add_argument("--paged-kernel", default="dots",
                   choices=("dots", "elementwise"),
                   help="paged-kernel math formulation (identical numerics; "
                        "the elementwise form is the Mosaic compile-risk "
                        "hedge — ops/paged_decode.py)")
    p.add_argument("--skip-uncached", action="store_true",
                   help="skip the slow full-forward reference path")
    p.add_argument("--chunk-prefill", action="store_true",
                   help="also bench the serving chunk-prefill attention "
                        "(ops/paged_decode.paged_chunk_attention): Pallas "
                        "kernel vs gathered-page XLA rows over "
                        "--chunk-sizes x --chunk-pages")
    p.add_argument("--chunk-sizes", default="64,128",
                   help="chunk-prefill query lengths C to sweep")
    p.add_argument("--chunk-pages", default="4,16",
                   help="live page counts to sweep for the chunk rows")
    p.add_argument("--chunk-heads", type=int, default=8)
    p.add_argument("--chunk-dh", type=int, default=64)
    p.add_argument("--chunk-page-size", type=int, default=64,
                   help="positions per page for the chunk rows")
    p.add_argument("--kv-dtype", default=None,
                   help="comma list among float32,bfloat16,int8: serving-"
                        "pool dtype sweep rows — paged flash-decode and "
                        "chunk-prefill attention, Pallas fused-dequant "
                        "kernel vs XLA reference per dtype (kernel rows "
                        "skipped-with-provenance off-TPU)")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.distributed import (backend_provenance,
                                          enable_compilation_cache,
                                          warn_cpu_fallback)

    enable_compilation_cache()
    # actual-backend record on every row + loud cpu-fallback banner (shared
    # classification — distributed.backend_provenance): without it a hung
    # TPU init would silently report cpu decode numbers as if on-chip,
    # exactly the poisoning bench.py/scalebench already guard against
    prov = backend_provenance(args.platform)
    warn_cpu_fallback(prov, "decodebench")

    from ddlbench_tpu.config import DATASETS
    from ddlbench_tpu.models import init_model
    from ddlbench_tpu.models.zoo import get_model
    import ddlbench_tpu.models.seq2seq as s2s

    spec = DATASETS[args.benchmark]
    model = get_model(args.model, spec)
    params, state, _ = init_model(model, jax.random.key(0))
    # seq2seq: prompt = the source segment. Token (causal-LM) benchmarks:
    # prompt = half the stream — the long-context decode shape where the
    # paged cache pays most (live pages vs masked full length).
    causal = spec.kind == "tokens"
    T = min(args.total_len or spec.seq_len, spec.seq_len)
    S = T // 2 if causal else spec.src_len
    src = jax.random.randint(jax.random.key(1), (args.batch, S), 0,
                             spec.num_classes, jnp.int32)
    new_tokens = (T - S) * args.batch

    import ddlbench_tpu.models.decode as dec
    from ddlbench_tpu.ops.paged_decode import set_paged_kernel_style

    set_paged_kernel_style(args.paged_kernel)

    # "paged": copy-on-write page-table cache + live-page flash decode
    # (ops/paged_decode.py) — the round-4 fast path; "cached": dense KV
    # cache with the full gather-per-expansion; "full": the full-forward
    # reference loop.
    runs = [("greedy", "paged"), ("beam", "paged"),
            ("greedy", "cached"), ("beam", "cached")]
    if not args.skip_uncached:
        runs += [("greedy", "full"), ("beam", "full")]

    for mode, variant in runs:
        cached = variant != "full"
        if variant == "paged" and not dec.supports_paged(model):
            print(json.dumps({"tool": "decodebench", "mode": mode,
                              "variant": "paged",
                              "skipped": f"{args.model} lacks paged support",
                              **prov}),
                  flush=True)
            continue
        if causal and variant == "full":
            # the full-forward reference loop is seq2seq-specific; the
            # causal cached path is pinned against it in tests instead
            print(json.dumps({"tool": "decodebench", "mode": mode,
                              "variant": "full",
                              "skipped": "full-forward loop is seq2seq-only",
                              **prov}),
                  flush=True)
            continue
        if variant == "paged" or causal:
            cdt = jnp.dtype(args.cache_dtype if variant == "paged"
                            else "float32")
            paged = variant == "paged"
            if mode == "greedy":
                fn = jax.jit(lambda: dec.greedy_decode(
                    model, params, state, src, T, dtype=cdt, paged=paged))
            else:
                fn = jax.jit(lambda: dec.beam_search_decode(
                    model, params, state, src, T, beam=args.beam,
                    dtype=cdt, paged=paged)[0])
        elif mode == "greedy":
            fn = jax.jit(lambda: s2s.greedy_decode(
                model, params, state, src, T, use_cache=cached))
        else:
            fn = jax.jit(lambda: s2s.beam_search_decode(
                model, params, state, src, T, beam=args.beam,
                use_cache=cached)[0])
        out = [None]

        def run():
            out[0] = fn()

        def sync():
            jax.tree.map(lambda a: float(jnp.sum(a)), out[0])

        try:
            dt = _bench(run, sync, args.repeats)
        except Exception as e:  # e.g. Mosaic rejects a kernel shape: record
            # the row and keep the sweep alive (lmbench hbm-oom row pattern)
            print(json.dumps({
                "tool": "decodebench", "mode": mode, "variant": variant,
                "error": f"{type(e).__name__}: {str(e).splitlines()[0][:200]}",
                **prov,
            }), flush=True)
            continue
        print(json.dumps({
            "tool": "decodebench",
            "platform": jax.devices()[0].platform,
            **prov,
            "model": args.model,
            "benchmark": args.benchmark,
            "mode": mode,
            "variant": variant,
            "cache_dtype": (args.cache_dtype if variant == "paged"
                            else "float32"),
            "cached": cached,
            "batch": args.batch,
            "prompt_len": S,
            "total_len": T,
            "beam": args.beam if mode == "beam" else 1,
            "new_tokens": new_tokens,
            "tokens_per_sec": round(new_tokens / dt, 2),
            "ms_per_token": round(1000.0 * dt / max(1, T - S), 3),
        }), flush=True)

    if args.chunk_prefill:
        _chunk_prefill_rows(args, prov)
    if args.kv_dtype:
        _kv_dtype_rows(args, prov)
    return 0


def _chunk_prefill_rows(args, prov) -> None:
    """Kernel-vs-XLA rows for the serving chunk-prefill attention: one row
    per (chunk size C, live page count) x {chunk-kernel, chunk-xla} over a
    synthetic serving pool (shuffled free-list table, the layout the
    engine produces). The kernel variant is the Pallas multi-query
    flash-decode analog and only compiles on TPU — elsewhere the row is
    recorded as skipped, with the same backend provenance as every other
    row, so a cpu-fallback run can never masquerade as a chip number."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddlbench_tpu.distributed import is_tpu_backend
    from ddlbench_tpu.ops.paged_decode import paged_chunk_attention

    H, dh, page = args.chunk_heads, args.chunk_dh, args.chunk_page_size
    chunks = [int(x) for x in args.chunk_sizes.split(",")]
    pages = [int(x) for x in args.chunk_pages.split(",")]
    for C in chunks:
        for npl in pages:
            pool_pages = npl + 2  # slot 0 scratch + headroom
            kk = jax.random.normal(jax.random.key(10),
                                   (pool_pages, page, H, dh), jnp.float32)
            vv = jax.random.normal(jax.random.key(11),
                                   (pool_pages, page, H, dh), jnp.float32)
            perm = np.random.default_rng(0).permutation(
                np.arange(1, pool_pages))[:npl]
            cache = {"pool_k": kk, "pool_v": vv,
                     "table": jnp.asarray(perm[None, :], jnp.int32)}
            q = jax.random.normal(jax.random.key(12), (1, H, C, dh),
                                  jnp.float32)
            # chunk start = the last page (the serving frontier shape)
            start = jnp.int32((npl - 1) * page)
            for variant, use_kernel in (("chunk-kernel", True),
                                        ("chunk-xla", False)):
                base = {"tool": "decodebench", "variant": variant,
                        "chunk": C, "pages": npl, "page": page,
                        "heads": H, "dh": dh, **prov}
                if use_kernel and not is_tpu_backend():
                    print(json.dumps({
                        **base,
                        "skipped": "Pallas chunk kernel needs a TPU "
                                   "backend (XLA row is the CPU path)",
                    }), flush=True)
                    continue
                fn = jax.jit(lambda q=q, cache=cache, start=start,
                             uk=use_kernel: paged_chunk_attention(
                                 q, cache, start, npl, page=page,
                                 use_kernel=uk,
                                 kernel_style=args.paged_kernel))
                out = [None]

                def run():
                    out[0] = fn()

                def sync():
                    float(jnp.sum(out[0]))

                try:
                    dt = _bench(run, sync, args.repeats)
                except Exception as e:  # Mosaic shape rejection etc.
                    print(json.dumps({
                        **base,
                        "error": f"{type(e).__name__}: "
                                 f"{str(e).splitlines()[0][:200]}",
                    }), flush=True)
                    continue
                print(json.dumps({
                    **base,
                    "tokens_per_sec": round(C / dt, 2),
                    "us_per_chunk": round(1e6 * dt, 2),
                }), flush=True)


def _kv_dtype_rows(args, prov) -> None:
    """KV-pool dtype sweep for the serving attention hot path: one row per
    (dtype, op in {decode, chunk}, variant in {kernel, xla}) over a
    synthetic shuffled-free-list pool at the ``--chunk-*`` shapes. The
    int8 pool is built through the real write primitive
    (paged_table_chunk_write — per-page scale sidecar + stochastic
    rounding), so the kernel rows measure the FUSED-dequant read path the
    serving engine compiles, not a hand-rolled stand-in. Kernel rows off
    TPU record skipped-with-provenance, the same contract as every other
    decodebench row."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddlbench_tpu.distributed import is_tpu_backend
    from ddlbench_tpu.ops.paged_decode import (paged_attention,
                                               paged_chunk_attention,
                                               paged_table_chunk_write,
                                               serve_pool_init)

    H, dh, page = args.chunk_heads, args.chunk_dh, args.chunk_page_size
    C = int(args.chunk_sizes.split(",")[0])
    npl = max(int(x) for x in args.chunk_pages.split(","))
    dtypes = [s.strip() for s in args.kv_dtype.split(",") if s.strip()]
    for name in dtypes:
        if name not in ("float32", "bfloat16", "int8"):
            print(json.dumps({"tool": "decodebench", "variant": "kv-dtype",
                              "kv_dtype": name,
                              "error": "unknown dtype (float32|bfloat16|"
                                       "int8)", **prov}), flush=True)
            continue
        dt = jnp.dtype(name)
        pool_pages = npl + 2  # slot 0 scratch + headroom
        perm = np.random.default_rng(0).permutation(
            np.arange(1, pool_pages))[:npl]
        table = jnp.asarray(perm[None, :], jnp.int32)
        pool = serve_pool_init(pool_pages, page, H, dh, dt)
        cache = {**pool, "table": table}
        # fill the live pages through the real page-aligned write path
        kk = jax.random.normal(jax.random.key(20), (1, npl * page, H, dh),
                               jnp.float32)
        vv = jax.random.normal(jax.random.key(21), (1, npl * page, H, dh),
                               jnp.float32)
        cache = jax.jit(lambda c, k, v: paged_table_chunk_write(
            c, k, v, jnp.int32(0), page))(cache, kk, vv)
        q1 = jax.random.normal(jax.random.key(22), (1, H, dh), jnp.float32)
        qC = jax.random.normal(jax.random.key(23), (1, H, C, dh),
                               jnp.float32)
        pos = jnp.asarray([npl * page - 1], jnp.int32)
        start = jnp.asarray([(npl - 1) * page], jnp.int32)
        ops = [
            ("decode", 1, lambda uk: paged_attention(
                q1, cache, pos, npl, page=page, use_kernel=uk,
                kernel_style=args.paged_kernel)),
            ("chunk", C, lambda uk: paged_chunk_attention(
                qC, cache, start, npl, page=page, use_kernel=uk,
                kernel_style=args.paged_kernel)),
        ]
        for op_name, toks, fn0 in ops:
            for variant, use_kernel in (("kernel", True), ("xla", False)):
                base = {"tool": "decodebench", "variant": "kv-dtype",
                        "op": op_name, "kv_dtype": name,
                        "kernel": use_kernel, "chunk": C, "pages": npl,
                        "page": page, "heads": H, "dh": dh, **prov}
                if use_kernel and not is_tpu_backend():
                    print(json.dumps({
                        **base,
                        "skipped": "Pallas fused-dequant kernel needs a "
                                   "TPU backend (XLA row is the CPU "
                                   "path)"}), flush=True)
                    continue
                fn = jax.jit(lambda uk=use_kernel, f=fn0: f(uk))
                out = [None]

                def run():
                    out[0] = fn()

                def sync():
                    float(jnp.sum(out[0]))

                try:
                    dt_s = _bench(run, sync, args.repeats)
                except Exception as e:  # Mosaic shape rejection etc.
                    print(json.dumps({
                        **base,
                        "error": f"{type(e).__name__}: "
                                 f"{str(e).splitlines()[0][:200]}",
                    }), flush=True)
                    continue
                print(json.dumps({
                    **base,
                    "tokens_per_sec": round(toks / dt_s, 2),
                    "us_per_call": round(1e6 * dt_s, 2),
                }), flush=True)


if __name__ == "__main__":
    sys.exit(main())
