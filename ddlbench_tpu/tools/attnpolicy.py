"""Check the auto-dispatch decision table against measured attnbench sweeps.

Reads every ``perf_runs/attnsweep_*.json`` (and legacy attn_crossover.json)
produced by scripts/tpu_round4.sh's median-of-N sweeps, computes the
measured winner per (T, B, prefix) cell, and reports where
``models.transformer.flash_pays_off`` disagrees — the refresh loop VERDICT
r3 weak #2 asked for: policy from medians, re-checkable every round.

One JSON document on stdout:
    {"cells": [...], "disagreements": [...], "agreement_pct": N}

Cells inside the +-noise margin (default 7%) count as ties and never
disagree. Exit code 1 if any out-of-margin disagreement exists.

Usage:
    python -m ddlbench_tpu.tools.attnpolicy [--dir perf_runs]
        [--noise-margin 0.07]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def load_cells(run_dir: str):
    cells = []
    for path in sorted(glob.glob(os.path.join(run_dir, "attnsweep_*.json"))) \
            + [os.path.join(run_dir, "attn_crossover.json")]:
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "flash_speedup" in row:
                    cells.append({
                        "T": row["T"], "B": row["B"],
                        "prefix": row.get("prefix", 0),
                        "flash_speedup": row["flash_speedup"],
                        # rows without a repeats stamp predate median
                        # support (the round-3 single-shot sweep)
                        "repeats": row.get("repeats", 1),
                        "source": os.path.basename(path),
                    })
    return cells


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dir", default="perf_runs")
    p.add_argument("--noise-margin", type=float, default=0.07,
                   help="speedups within 1 +- margin count as ties")
    args = p.parse_args(argv)

    from ddlbench_tpu.models.transformer import flash_pays_off

    raw = load_cells(args.dir)
    # aggregate repeated measurements of the same (T, B, prefix) cell to the
    # MEDIAN — legacy single-shot rows (attn_crossover.json) and fresh
    # median-of-5 sweeps judge each cell once, not once per artifact line
    import statistics

    by_cell: dict = {}
    for c in raw:
        by_cell.setdefault((c["T"], c["B"], c["prefix"]), []).append(c)
    cells = []
    for (T, B, prefix), rows in sorted(by_cell.items()):
        cells.append({
            "T": T, "B": B, "prefix": prefix,
            "flash_speedup": round(statistics.median(
                r["flash_speedup"] for r in rows), 3),
            "num_measurements": len(rows),
            # a cell is trustworthy once ANY of its rows was itself a
            # median over >= 3 timed loops (attnbench --repeats); the
            # round-3 single-shot rows only ever count as provisional
            "measured_with_medians": any(r["repeats"] >= 3 for r in rows),
            "sources": sorted({r["source"] for r in rows}),
        })
    disagreements = []
    decided = 0
    for c in cells:
        s = c["flash_speedup"]
        lo, hi = 1.0 - args.noise_margin, 1.0 + args.noise_margin
        if lo <= s <= hi:
            c["winner"] = "tie"
            continue
        c["winner"] = "flash" if s > 1.0 else "xla"
        c["policy"] = ("flash" if flash_pays_off(c["T"], c["B"], c["prefix"])
                       else "xla")
        decided += 1
        if c["policy"] != c["winner"]:
            disagreements.append(c)
    # only median-backed cells gate (exit code); single-shot legacy rows are
    # reported as provisional — the exact noise the policy exists to discount
    hard = [c for c in disagreements if c["measured_with_medians"]]
    doc = {
        "num_cells": len(cells),
        "num_decided": decided,
        "agreement_pct": round(
            100.0 * (decided - len(disagreements)) / max(1, decided), 1),
        "disagreements": hard,
        "provisional_disagreements": [
            c for c in disagreements if not c["measured_with_medians"]],
        "cells": cells,
    }
    print(json.dumps(doc))
    return 1 if hard else 0


if __name__ == "__main__":
    sys.exit(main())
