"""auditbench: the CPU-runnable compiled-program audit gate.

Two verbs:

``run``
    Compile the tieable engine matrix at tiny shapes (dp ZeRO-1 bucketed,
    dp int8 incl. scale sidecars, gpipe replicated + hybrid ZeRO-1, the
    Megatron-in-stage tp pipeline) plus the serve-program layouts
    (kv_dtype x tp), extract each program's audit manifest
    (telemetry/audit.py — flops / HBM components / per-collective ledger
    out of the optimized HLO), cross-check ``comm_stats`` and
    ``pool_page_bytes`` against them, and write one ledger JSON. Exits
    nonzero when any tie-out fails — every analytic byte formula is
    checked against the program XLA actually built, on any backend.

``diff``
    Compare two ledgers (e.g. the committed golden in
    ``perf_runs/audit_golden/`` vs a fresh run): unexplained growth in
    flops / peak HBM / wire bytes / per-kind collective counts exits
    nonzero — the regression gate the bench trajectory lacks while
    on-chip rounds queue behind the TPU tunnel.

Examples::

    python -m ddlbench_tpu.tools.auditbench run --out /tmp/audit.json
    python -m ddlbench_tpu.tools.auditbench diff \
        perf_runs/audit_golden/cpu8.json /tmp/audit.json

The virtual 8-device CPU mesh must be up before jax initializes::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python -m ddlbench_tpu.tools.auditbench run ...
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def _train_matrix():
    """The tieable train-engine matrix at tiny shapes: (name, cfg)."""
    from ddlbench_tpu.config import RunConfig

    base = dict(benchmark="mnist", num_devices=8, compute_dtype="float32",
                batch_size=2, steps_per_epoch=2)
    pipe = dict(benchmark="mnist", strategy="gpipe", num_devices=8,
                num_stages=4, dp_replicas=2, micro_batch_size=2,
                num_microbatches=4, compute_dtype="float32",
                steps_per_epoch=2)
    tpp = dict(benchmark="synthtext", arch="transformer_t",
               strategy="gpipe", num_devices=8, num_stages=2, tp_size=2,
               dp_replicas=2, micro_batch_size=2, num_microbatches=4,
               compute_dtype="float32", steps_per_epoch=2)
    matrix = [
        ("train/dp-zero1-b3",
         RunConfig(strategy="dp", dp_shard_update=True, comm_buckets=3,
                   **base)),
        ("train/dp-zero1-int8-b3",
         RunConfig(strategy="dp", dp_shard_update=True, comm_buckets=3,
                   allreduce_dtype="int8", **base)),
        ("train/gpipe-dp2", RunConfig(**pipe)),
        ("train/gpipe-dp2-zero1",
         RunConfig(dp_shard_update=True, **pipe)),
        ("train/tpp-s2-tp2-dp2", RunConfig(**tpp)),
    ]
    for _, cfg in matrix:
        cfg.validate()
    return matrix


def _serve_matrix():
    from ddlbench_tpu.config import ServeConfig

    out = []
    for kv in ("float32", "int8"):
        for tp in (1, 2):
            cfg = ServeConfig(max_batch=4, pool_pages=20, page=4,
                              max_len=16, prefill_chunk=4, kv_dtype=kv,
                              tp=tp)
            out.append((f"serve/kv={kv}/tp={tp}", cfg))
    return out


def run_audits(out_path: Optional[str], include_serve: bool = True,
               quiet: bool = False) -> int:
    import jax

    from ddlbench_tpu.distributed import record_provenance
    from ddlbench_tpu.models import init_model
    from ddlbench_tpu.models.zoo import get_model
    from ddlbench_tpu.config import DATASETS
    from ddlbench_tpu.serve.engine import ServeEngine
    from ddlbench_tpu.telemetry.audit import (audit_serve_engine,
                                              audit_train_config,
                                              write_manifests)

    prov = record_provenance(None, "auditbench")
    manifests = []
    failed: List[str] = []

    for name, cfg in _train_matrix():
        man, _ = audit_train_config(cfg, name)
        manifests.append(man)
        rec = man["reconcile"]
        ok = rec.get("ok", False)
        if not ok:
            failed.append(name)
        if not quiet:
            n_bad = sum(1 for c in rec["checks"] if not c["ok"])
            print(f"{name}: tieable={rec['tieable']} ok={ok} "
                  f"checks={len(rec['checks'])} failed={n_bad} "
                  f"unexplained={len(rec['unexplained'])} "
                  f"wire={man['wire_bytes_total']:.0f}B", flush=True)

    if include_serve:
        spec = DATASETS["synthtext"]
        model = get_model("transformer_t", spec)
        params, state, _ = init_model(model, jax.random.key(0))
        for name, scfg in _serve_matrix():
            eng = ServeEngine(model, params, state, scfg)
            mans, pool = audit_serve_engine(eng, prefix=name)
            manifests.extend(mans)
            if not pool["ok"]:
                failed.append(name)
            if not quiet:
                print(f"{name}: pool_ok={pool['ok']} "
                      f"page_bytes={pool['pool_page_bytes']:.0f} "
                      f"programs={len(mans)}", flush=True)

    if out_path:
        write_manifests(out_path, manifests, header=prov)
        if not quiet:
            print(f"wrote {len(manifests)} manifests -> {out_path}",
                  flush=True)
    if failed:
        print(f"AUDIT FAILED: {', '.join(failed)}", file=sys.stderr,
              flush=True)
        return 1
    return 0


def run_diff(old_path: str, new_path: str, tolerance: float,
             quiet: bool = False) -> int:
    from ddlbench_tpu.telemetry.audit import (diff_manifests,
                                              load_manifests)

    report = diff_manifests(load_manifests(old_path),
                            load_manifests(new_path), tolerance=tolerance)
    if not quiet:
        print(f"compared {len(report['compared'])} programs "
              f"(+{len(report['added'])} added, "
              f"-{len(report['removed'])} removed)", flush=True)
        for r in report["regressions"]:
            growth = (f"{r['growth'] * 100:+.1f}%"
                      if r["growth"] not in (float("inf"),) else "new")
            print(f"REGRESSION {r['program']}: {r['metric']} "
                  f"{r['old']:.0f} -> {r['new']:.0f} ({growth})",
                  flush=True)
    if not report["ok"]:
        print(f"auditbench diff: {len(report['regressions'])} "
              f"unexplained regression(s)", file=sys.stderr, flush=True)
        return 1
    if not quiet:
        print("auditbench diff: clean", flush=True)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="auditbench",
        description="compiled-program audit gate (telemetry/audit.py)")
    sub = p.add_subparsers(dest="verb", required=True)
    pr = sub.add_parser("run", help="audit the engine matrix")
    pr.add_argument("--out", default=None,
                    help="write the ledger JSON here (atomic)")
    pr.add_argument("--no-serve", action="store_true",
                    help="skip the serve-program layouts")
    pr.add_argument("--quiet", action="store_true")
    pd = sub.add_parser("diff", help="diff two ledgers; nonzero on growth")
    pd.add_argument("old")
    pd.add_argument("new")
    pd.add_argument("--tolerance", type=float, default=None,
                    help="relative growth tolerated before flagging "
                         "(default telemetry/audit.DIFF_TOLERANCE)")
    pd.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    if args.verb == "run":
        from ddlbench_tpu.distributed import force_host_mesh_platform

        force_host_mesh_platform()
        return run_audits(args.out, include_serve=not args.no_serve,
                          quiet=args.quiet)
    from ddlbench_tpu.telemetry.audit import DIFF_TOLERANCE

    tol = args.tolerance if args.tolerance is not None else DIFF_TOLERANCE
    return run_diff(args.old, args.new, tol, quiet=args.quiet)


if __name__ == "__main__":
    raise SystemExit(main())
