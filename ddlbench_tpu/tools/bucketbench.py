"""Fixed-shape vs length-bucketed translation batching, measured.

The framework's fixed-shape choice for the seq2seq workload is priced
analytically by ``TranslationData.bucketing_report`` (padding efficiency vs
per-bucket recompiles — data/translation.py module docstring). VERDICT r3
next #9 asked for the empirical point: this tool actually IMPLEMENTS
bucketed batching and measures both modes end to end on one chip.

Method: synthesize a parallel corpus with a realistic (lognormal) length
distribution, tokenize once, then train the SAME rows two ways:

* fixed: every batch packed at the spec shape (S, T) — one compile;
* bucketed: each pair packed at the smallest grid bucket that fits it —
  one seq2seq model variant per bucket (attention masks and position
  slices are shape-derived, so ALL variants share one set of parameters
  and one optimizer state; the train step compiles once per bucket).

The metric that decides the design is VALID (non-pad) tokens/sec over the
whole epoch: both modes process identical text, so the ratio is pure
padding-efficiency win vs bucket-compile + small-batch-shape cost.

One JSON line per mode + a summary line:
    {"mode": "bucketed", "valid_tokens_per_sec": N, "num_compiles": 4, ...}

Usage:
    python -m ddlbench_tpu.tools.bucketbench [-m seq2seq_s] [--pairs 4096]
        [--batch 64] [--src-len 128] [--tgt-len 128] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def synth_corpus(path: str, n_pairs: int, seed: int = 0) -> None:
    """Parallel corpus with lognormal sentence lengths (mean ~12 words,
    heavy tail) over a small word vocabulary — enough structure for BPE."""
    import numpy as np

    rng = np.random.default_rng(seed)
    words = [f"w{i:03d}" for i in range(200)]

    def sentence(mean_words: float) -> str:
        n = max(1, int(rng.lognormal(mean=np.log(mean_words), sigma=0.6)))
        return " ".join(rng.choice(words, size=n))

    os.makedirs(path, exist_ok=True)
    for split, count in (("train", n_pairs), ("test", max(32, n_pairs // 10))):
        with open(os.path.join(path, f"{split}.src"), "w") as fs, \
                open(os.path.join(path, f"{split}.tgt"), "w") as ft:
            for _ in range(count):
                fs.write(sentence(12) + "\n")
                ft.write(sentence(13) + "\n")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-m", "--model", default="seq2seq_s")
    p.add_argument("--pairs", type=int, default=4096)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--src-len", type=int, default=128)
    p.add_argument("--tgt-len", type=int, default=128)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--corpus-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddlbench_tpu.config import DatasetSpec, RunConfig
    from ddlbench_tpu.data.synthetic import mask_source_labels
    from ddlbench_tpu.data.translation import (PAD, TranslationData,
                                               _pack, _read_pairs,
                                               find_parallel_corpus)
    from ddlbench_tpu.distributed import enable_compilation_cache
    from ddlbench_tpu.models.layers import init_model
    from ddlbench_tpu.models.seq2seq import build_seq2seq
    from ddlbench_tpu.parallel.single import SingleStrategy

    enable_compilation_cache()
    import ddlbench_tpu.models.seq2seq as s2s

    # tiny variant for CPU smokes (same registration as the test suite)
    s2s._VARIANTS.setdefault("seq2seq_t",
                             dict(d_model=32, n_layers=2, n_heads=4))
    S, T = args.src_len, args.tgt_len
    corpus = args.corpus_dir or os.path.join(
        tempfile.gettempdir(),
        f"ddlb_bucket_corpus_{args.pairs}_s{args.seed}")
    if not find_parallel_corpus(corpus, "train"):
        synth_corpus(corpus, args.pairs, args.seed)

    spec = DatasetSpec("bucketmt", (S + T,), 32_768, args.pairs,
                       args.pairs // 10, kind="seq2seq", src_len=S)
    data = TranslationData(corpus, spec, args.batch)
    report = data.bucketing_report()
    tok = data.tokenizer
    pairs = _read_pairs(*find_parallel_corpus(corpus, "train"))

    # one parameter set serves every bucket shape: attention masks and
    # position-table slices are derived from the input shape at apply time
    cfg = RunConfig(benchmark="synthmt", strategy="single", arch=args.model,
                    batch_size=args.batch, compute_dtype=args.dtype,
                    steps_per_epoch=1)
    full_model = build_seq2seq(args.model, (S + T,), spec.num_classes, S)
    strat_full = SingleStrategy(full_model, cfg)
    ts0 = strat_full.init(jax.random.key(0))
    lr = jnp.float32(1e-4)

    def batches_from_rows(rows: np.ndarray, src_len: int):
        """[N, S_b + T_b + 1] -> list of (x, labels) batches (drop tail)."""
        out = []
        for i in range(rows.shape[0] // args.batch):
            ids = jnp.asarray(rows[i * args.batch:(i + 1) * args.batch])
            x, labels = ids[:, :-1], ids[:, 1:]
            labels = mask_source_labels(labels, src_len)
            labels = jnp.where((labels == PAD) | (x == PAD), -1, labels)
            out.append((x, labels))
        return out

    def run_mode(mode: str, shard_lists):
        """shard_lists: [(strategy, src_len, batches, valid_tokens)]."""
        # fresh copy per mode: the donated train_state would otherwise be
        # consumed by the first mode's run
        ts = jax.tree.map(jnp.copy, ts0)
        compile_s = 0.0
        n_compiles = 0
        # compile each distinct shape once (not charged to throughput;
        # reported separately — the cost bucketing adds)
        if not any(batches for _, _, batches, _ in shard_lists):
            raise SystemExit(
                f"not enough pairs for one batch of {args.batch} in any "
                f"shape — raise --pairs or lower --batch")
        for strat, _, batches, _ in shard_lists:
            if not batches:
                continue
            t0 = time.perf_counter()
            # train_step donates ts: chain it (the warmup is a real step)
            ts, m = strat.train_step(ts, *batches[0], lr)
            float(m["loss"])
            compile_s += time.perf_counter() - t0
            n_compiles += 1
        t0 = time.perf_counter()
        total_valid = 0
        total_rows = 0
        for strat, _, batches, valid in shard_lists:
            for x, y in batches:
                ts, m = strat.train_step(ts, x, y, lr)
                total_rows += x.shape[0]
            total_valid += valid
        float(m["loss"])  # device sync
        dt = time.perf_counter() - t0
        return {
            "tool": "bucketbench", "mode": mode, "model": args.model,
            "batch": args.batch, "rows_trained": total_rows,
            "valid_tokens": int(total_valid),
            "valid_tokens_per_sec": round(total_valid / dt, 1),
            "steady_sec": round(dt, 3),
            "num_compiles": n_compiles,
            "compile_sec": round(compile_s, 1),
            "platform": jax.devices()[0].platform,
        }

    # ---- fixed: all rows at (S, T) --------------------------------------
    rows_fixed, lens_fixed = _pack(tok, pairs, S, T)
    n_batches = rows_fixed.shape[0] // args.batch
    kept = n_batches * args.batch
    valid_fixed = int(lens_fixed[:kept].sum())
    fixed = run_mode("fixed", [
        (strat_full, (S, T), batches_from_rows(rows_fixed[:kept], S),
         valid_fixed)])
    fixed["padding_efficiency"] = round(report["fixed_efficiency"], 4)
    print(json.dumps(fixed), flush=True)

    # ---- bucketed: smallest grid bucket that fits each pair -------------
    grid = [(S // 4, T // 4), (S // 2, T // 2), (3 * S // 4, 3 * T // 4),
            (S, T)]
    # bucket criterion from the ONE full-shape _pack above: lens_fixed
    # holds (src_len clipped at S, [BOS]+tgt(+EOS) len clipped at T+1) per
    # pair — clipping only affects pairs that belong in the last bucket
    # anyway, so no re-encoding is needed
    assigned = [False] * len(pairs)
    shard_lists = []
    for gs, gt in grid:
        # smallest bucket that fits: src <= gs and [BOS]+tgt(+EOS) <= gt+1;
        # the last (spec-shape) bucket takes every remaining pair so
        # over-long pairs are truncated exactly as the fixed mode does
        last = (gs, gt) == grid[-1]
        take = [i for i in range(len(pairs))
                if not assigned[i]
                and (last or (lens_fixed[i][0] <= gs
                              and lens_fixed[i][1] <= gt + 1))]
        nb = len(take) // args.batch
        kept_b = nb * args.batch
        if not kept_b:
            continue
        # only pairs that actually train here are consumed; batch-tail
        # pairs fall through to a bigger bucket instead of dropping
        take = take[:kept_b]
        for i in take:
            assigned[i] = True
        rows_b, lens_b = _pack(tok, [pairs[i] for i in take], gs, gt)
        bmodel = build_seq2seq(args.model, (gs + gt,), spec.num_classes, gs)
        strat_b = SingleStrategy(bmodel, cfg)
        shard_lists.append((strat_b, (gs, gt),
                            batches_from_rows(rows_b, gs),
                            int(lens_b.sum())))
    leftover = sum(1 for a in assigned if not a)
    if leftover:
        print(json.dumps({"tool": "bucketbench", "note":
                          f"{leftover} batch-tail pairs train in no "
                          f"bucket (dropped from the bucketed pass)"}),
              flush=True)
    bucketed = run_mode("bucketed", shard_lists)
    bucketed["padding_efficiency"] = round(report["bucketed_efficiency"], 4)
    bucketed["buckets"] = [
        {"shape": list(s[1]), "batches": len(s[2])} for s in shard_lists]
    print(json.dumps(bucketed), flush=True)

    print(json.dumps({
        "tool": "bucketbench", "mode": "summary",
        "bucketed_over_fixed_steady": round(
            bucketed["valid_tokens_per_sec"] / fixed["valid_tokens_per_sec"],
            3),
        "extra_compiles": bucketed["num_compiles"] - fixed["num_compiles"],
        "extra_compile_sec": round(
            bucketed["compile_sec"] - fixed["compile_sec"], 1),
        "analytic_efficiency_ratio": round(
            report["bucketed_efficiency"] / report["fixed_efficiency"], 3),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
