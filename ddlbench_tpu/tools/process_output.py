"""Log scraper — process_output analog (SURVEY.md §2 C12).

The reference validates runs post-hoc by scraping its print-based logs
(pipedream-fork/runtime/scripts/process_output.py, process_output_gnmt.py):
regexes over ``slurm.out`` pull per-epoch throughput/loss/accuracy into a
summary. This framework emits structured JSONL directly (``--jsonl``), but the
scraper exists anyway to prove the printed schema (train/metrics.py) really is
machine-parseable and to process logs from runs where JSONL wasn't enabled.

Usage:
    python -m ddlbench_tpu.tools.process_output run.log [run2.log ...]

Prints one JSON summary per input file:
    {"file": ..., "epochs": N, "train_intervals": N,
     "samples_per_sec_avg": X, "sec_per_epoch_avg": S,
     "final_valid_accuracy": A, "per_epoch": [{...}, ...],
     "comm_mb_per_step": M|null, "manifest": {...}|null}
"""

from __future__ import annotations

import json
import re
import sys
from typing import Any, Dict, List

TRAIN_RE = re.compile(
    r"train \| (?P<epoch>\d+)/(?P<total>\d+) epoch \((?P<pct>[\d.]+)%\) \| "
    r"(?P<sps>[\d.]+) samples/sec \| loss (?P<loss>[-\d.naife]+) \| "
    r"mem (?P<mem>[\d.]+) GB in use, (?P<peak>[\d.]+) GB peak"
)
EPOCH_RE = re.compile(
    r"epoch (?P<epoch>\d+)/(?P<total>\d+) done \| (?P<sps>[\d.]+) samples/sec \| "
    r"(?P<sec>[\d.]+) sec(?: \| input stall (?P<stall>[\d.]+) ms)?"
    r"(?: \| step p50 (?P<p50>[\d.]+) ms, p95 (?P<p95>[\d.]+) ms)?"
)
VALID_RE = re.compile(
    r"valid \| (?P<epoch>\d+)/(?P<total>\d+) epoch \| loss (?P<loss>[-\d.naife]+) \| "
    r"accuracy (?P<acc>[\d.]+)(?: \| top5 (?P<top5>[\d.]+))?"
)
SUMMARY_RE = re.compile(
    r"valid accuracy: (?P<acc>[\d.]+) \| (?P<sps>[\d.]+) samples/sec, "
    r"(?P<sec>[\d.]+) sec/epoch \(average\)"
)
COMM_RE = re.compile(r"comm volume/step: (?P<mb>[\d.]+) MB")
MANIFEST_RE = re.compile(r"run manifest: (?P<json>\{.*\})")


def scrape(text: str) -> Dict[str, Any]:
    """Parse one run's log text into a summary dict."""
    intervals: List[Dict[str, float]] = []
    epochs: Dict[int, Dict[str, float]] = {}
    # Present (as null) even when the run died before the summary line.
    summary: Dict[str, Any] = {
        "final_valid_accuracy": None,
        "samples_per_sec_avg": None,
        "sec_per_epoch_avg": None,
    }
    comm_mb = None
    manifest = None
    for line in text.splitlines():
        if m := TRAIN_RE.search(line):
            intervals.append(
                {
                    "epoch": int(m["epoch"]),
                    "progress_pct": float(m["pct"]),
                    "samples_per_sec": float(m["sps"]),
                    "loss": float(m["loss"]),
                    "mem_peak_gb": float(m["peak"]),
                }
            )
        elif m := EPOCH_RE.search(line):
            e = int(m["epoch"])
            epochs.setdefault(e, {"epoch": e})
            epochs[e]["samples_per_sec"] = float(m["sps"])
            epochs[e]["epoch_seconds"] = float(m["sec"])
            if m["stall"]:  # input-stall suffix (async input pipeline)
                epochs[e]["input_stall_ms"] = float(m["stall"])
            if m["p50"]:  # step-latency suffix (telemetry/stats.py)
                epochs[e]["step_time_p50_ms"] = float(m["p50"])
                epochs[e]["step_time_p95_ms"] = float(m["p95"])
        elif m := VALID_RE.search(line):
            e = int(m["epoch"])
            epochs.setdefault(e, {"epoch": e})
            epochs[e]["valid_loss"] = float(m["loss"])
            epochs[e]["valid_accuracy"] = float(m["acc"])
            if m["top5"]:
                epochs[e]["valid_top5"] = float(m["top5"])
        elif m := SUMMARY_RE.search(line):
            summary = {
                "final_valid_accuracy": float(m["acc"]),
                "samples_per_sec_avg": float(m["sps"]),
                "sec_per_epoch_avg": float(m["sec"]),
            }
        elif m := COMM_RE.search(line):
            comm_mb = float(m["mb"])
        elif m := MANIFEST_RE.search(line):
            try:
                manifest = json.loads(m["json"])
            except json.JSONDecodeError:
                pass
    per_epoch = [epochs[e] for e in sorted(epochs)]
    return {
        "epochs": len(per_epoch),
        "train_intervals": len(intervals),
        "per_epoch": per_epoch,
        "comm_mb_per_step": comm_mb,
        "manifest": manifest,
        **summary,
    }


def main(argv=None) -> int:
    paths = (argv if argv is not None else sys.argv[1:]) or []
    if not paths:
        print("usage: python -m ddlbench_tpu.tools.process_output LOG [LOG...]",
              file=sys.stderr)
        return 2
    for path in paths:
        with open(path) as f:
            out = scrape(f.read())
        out["file"] = path
        print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
