"""Attention-kernel microbenchmark: flash (Pallas) vs XLA across sequence
lengths.

The evidence behind ``FLASH_AUTO_MIN_SEQ`` (models/transformer.py): one
fwd+bwd jitted step per (backend, T) cell over the bare attention primitive,
so the crossover where the kernel's grid/stream overhead stops paying for
its HBM savings can be re-measured when shapes, kernels, or hardware change.
One JSON line per T:

    {"T": 1024, "B": 16, ..., "flash_ms": N, "xla_ms": N, "flash_speedup": N}

Sync discipline follows tools/timing.py: chain nothing (the primitive is
stateless) but force a device->host transfer per timed region, because on
the axon tunnel block_until_ready can return early.

Usage:
    python -m ddlbench_tpu.tools.attnbench [--seq-lens 128,256,512,1024]
        [--batch 16] [--heads 8] [--head-dim 64] [--prefix 0] [--steps 50]
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-lens", default="128,256,512,768,1024,2048")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--prefix", type=int, default=0,
                   help="prefix-LM visible-prefix length (seq2seq shape)")
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--repeats", type=int, default=1,
                   help="timed loops per cell; the reported ms is the median "
                        "(the shared tunnel swings sub-640 cells run to run "
                        "— PERF.md auto-dispatch section)")
    p.add_argument("--dtype", default="bfloat16")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.distributed import enable_compilation_cache, is_tpu_backend
    from ddlbench_tpu.models.transformer import (causal_attention,
                                                 set_attention_backend)

    enable_compilation_cache()
    backends = ("flash", "xla") if is_tpu_backend() else ("xla",)
    dtype = jnp.dtype(args.dtype)

    def timed_once(f, *xs):
        o = f(*xs)
        float(jax.tree.leaves(o)[0].ravel()[0].astype(jnp.float32))
        t0 = time.perf_counter()
        for _ in range(args.steps):
            o = f(*xs)
        float(jax.tree.leaves(o)[0].ravel()[0].astype(jnp.float32))
        return (time.perf_counter() - t0) / args.steps

    def timed(f, *xs):
        import statistics
        return statistics.median(
            timed_once(f, *xs) for _ in range(max(1, args.repeats)))

    for T in (int(t) for t in args.seq_lens.split(",")):
        ks = jax.random.split(jax.random.key(0), 3)
        q, k, v = (jax.random.normal(kk, (args.batch, args.heads, T,
                                          args.head_dim), dtype) for kk in ks)

        def loss(q, k, v):
            out = causal_attention(q, k, v, prefix_len=args.prefix)
            return jnp.sum(out.astype(jnp.float32))

        row = {"T": T, "B": args.batch, "H": args.heads,
               "dh": args.head_dim, "prefix": args.prefix,
               "dtype": args.dtype, "repeats": args.repeats}
        for mode in backends:
            set_attention_backend(mode)
            try:
                g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
                row[f"{mode}_ms"] = round(timed(g, q, k, v) * 1e3, 3)
            finally:
                set_attention_backend("auto")
        if "flash_ms" in row and "xla_ms" in row:
            row["flash_speedup"] = round(row["xla_ms"] / row["flash_ms"], 3)
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
