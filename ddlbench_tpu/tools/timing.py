"""Shared benchmark timing ritual for bench.py / lmbench / scalebench.

One home for the measurement discipline so the copies cannot drift:
* warmup at least once (compilation stays out of the timed loop),
* time a loop whose train state chains step-to-step (so nothing overlaps
  past the measured region),
* sync via float(metrics["loss"]) — a device->host transfer — because on
  the experimental axon TPU tunnel block_until_ready can return before
  execution finishes, inflating throughput ~100x.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple


def timed_steps(run_step: Callable[[object, object], dict],
                get_batch: Callable[[int, int], Tuple[object, object]],
                steps: int, warmup: int) -> float:
    """Return the wall-clock seconds for ``steps`` chained train steps.

    ``run_step(x, y) -> metrics`` must thread its own train state (the chain
    is what makes float(loss) a full barrier); ``get_batch(epoch, step)``
    supplies batches (epoch 0 = warmup, 1 = timed)."""
    m = None
    x, y = get_batch(0, 0)
    for _ in range(max(1, warmup)):
        m = run_step(x, y)
    float(m["loss"])
    t0 = time.perf_counter()
    for step in range(steps):
        x, y = get_batch(1, step)
        m = run_step(x, y)
    float(m["loss"])
    return time.perf_counter() - t0


def timed_steps_prefetched(run_step: Callable[..., dict], prefetcher,
                           warmup: int) -> Tuple[float, float, int, list]:
    """``timed_steps`` driven by the async input pipeline.

    ``prefetcher`` is a data.prefetch.Prefetcher; the timed region consumes
    one full epoch-1 stream (so batch production + device placement overlap
    the steps, exactly as in the training loop) and returns
    ``(seconds, input_stall_seconds, steps, step_seconds)`` — the stall
    term is how much of the measured wall clock was spent blocked waiting
    on input, ``steps`` is the number of steps actually driven (the
    stream's epoch length; callers must derive throughput from it, not
    from their own step count), and ``step_seconds`` is the per-step
    dispatch wall time (ring wait excluded — it is the stall), feeding the
    p50/p95 step-latency fields of bench.py's JSON. Same discipline as
    timed_steps: warmup outside the clock, chained state, float(loss) as
    the closing barrier."""
    m = None
    batch = prefetcher.shard_fn(*prefetcher.data.batch(0, 0))
    for _ in range(max(1, warmup)):
        m = run_step(*batch)
    float(m["loss"])
    # clock starts BEFORE the stream spawns its producer (training-loop
    # parity: loop.py takes its epoch tick before prefetch.stream) — a
    # pre-clock head start of depth batches would bias both dt and the
    # stall figure optimistic
    t0 = time.perf_counter()
    stream = prefetcher.stream(1, train=True)
    steps = 0
    step_s = []
    try:
        for fetched in stream:
            ts0 = time.perf_counter()
            m = run_step(*fetched.batch)
            step_s.append(time.perf_counter() - ts0)
            steps += 1
        float(m["loss"])
        dt = time.perf_counter() - t0
    finally:
        stream.close()
    return dt, stream.stall_s, steps, step_s
