"""Shared benchmark timing ritual for bench.py / lmbench / scalebench.

One home for the measurement discipline so the copies cannot drift:
* warmup at least once (compilation stays out of the timed loop),
* time a loop whose train state chains step-to-step (so nothing overlaps
  past the measured region),
* sync via float(metrics["loss"]) — a device->host transfer — because on
  the experimental axon TPU tunnel block_until_ready can return before
  execution finishes, inflating throughput ~100x.
"""

from __future__ import annotations

import time
from typing import Callable, Tuple


def timed_steps(run_step: Callable[[object, object], dict],
                get_batch: Callable[[int, int], Tuple[object, object]],
                steps: int, warmup: int) -> float:
    """Return the wall-clock seconds for ``steps`` chained train steps.

    ``run_step(x, y) -> metrics`` must thread its own train state (the chain
    is what makes float(loss) a full barrier); ``get_batch(epoch, step)``
    supplies batches (epoch 0 = warmup, 1 = timed)."""
    m = None
    x, y = get_batch(0, 0)
    for _ in range(max(1, warmup)):
        m = run_step(x, y)
    float(m["loss"])
    t0 = time.perf_counter()
    for step in range(steps):
        x, y = get_batch(1, step)
        m = run_step(x, y)
    float(m["loss"])
    return time.perf_counter() - t0
