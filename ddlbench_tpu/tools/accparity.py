"""Accuracy-parity benchmark: every engine trains REAL data to accuracy.

The reference's protocol is anchored on per-epoch validation accuracy on real
datasets (benchmark/mnist/mnist_pytorch.py:102-133, final summary :225-226;
PipeDream logs prec@1/5, runtime/image_classification/main_with_runtime.py:
639-653). Loss decreasing on synthetic random-label batches cannot catch
subtly-wrong training semantics — BN statistics handling, dp's lr x world
scaling, pipedream's weight-stashing staleness, the hetero conveyor's
intra-stage batch split all meet their one end-to-end check here: the SAME
real dataset trained under every engine must reach the SAME accuracy.

Dataset: sklearn's bundled handwritten digits (1797 real 8x8 scans — the one
real image dataset available in this zero-egress environment; MNIST/CIFAR
archives are not shipped), exported as MNIST IDX at 28x28 by
data/digits.export_digits_idx and served through the framework's standard
real-data ingest (imagefolder.import_mnist_idx -> native raw store).

Each engine runs through the PUBLIC CLI in a subprocess (fresh backend per
engine, XLA_FLAGS virtual CPU mesh applied at init) and is scraped from its
``result:`` line — the same machine interface the reference's
process_output.py scrapers rely on.

One JSON document on stdout:
    {"dataset": ..., "engines": {...}, "final_spread": s, "pass": true}

Usage:
    python -m ddlbench_tpu.tools.accparity [--epochs 20] [--lr 0.05]
        [--arch lenet] [--threshold 0.97] [--max-spread 0.02]
        [--engines single,dp,gpipe,pipedream,hetero] [--data-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

# Engine -> extra CLI argv. Global batch 32 everywhere it divides evenly;
# hetero's uneven (1,3) plan needs micro_batch % 3 == 0, so it runs 30
# (documented in the artifact). lr is NOT scaled here — dp applies its own
# Horovod-parity lr x world scaling internally, which is part of what this
# benchmark validates.
ENGINES = {
    "single": ["-f", "single", "--batch-size", "32"],
    "dp": ["-f", "dp", "-g", "2", "--batch-size", "32"],
    # explicit collective engine (parallel/dp.py): ZeRO-1 sharded weight
    # update, and the EQuARX-style bf16 compressed allreduce — the
    # accuracy-parity gate for --allreduce-dtype bf16 lives HERE (the f32
    # sharded update is pinned bitwise by tests/test_dp_shard.py)
    "dp-shard": ["-f", "dp", "-g", "2", "--batch-size", "32",
                 "--dp-shard-update"],
    "dp-bf16": ["-f", "dp", "-g", "2", "--batch-size", "32",
                "--allreduce-dtype", "bf16"],
    "dp-shard-bf16": ["-f", "dp", "-g", "2", "--batch-size", "32",
                      "--dp-shard-update", "--allreduce-dtype", "bf16"],
    # int8 wire (absmax + stochastic rounding, quarter gradient bytes): the
    # digits-parity gate for --allreduce-dtype int8 — the ONLY accuracy
    # claim the int8 path makes (ISSUE 6); same harness as the bf16 gate
    "dp-int8": ["-f", "dp", "-g", "2", "--batch-size", "32",
                "--allreduce-dtype", "int8"],
    "dp-shard-int8": ["-f", "dp", "-g", "2", "--batch-size", "32",
                      "--dp-shard-update", "--allreduce-dtype", "int8"],
    # overlapped engine (bucketed RS + just-in-time AG): f32 is bitwise-
    # pinned by tests/test_comm_overlap.py; this row is the end-to-end
    # digits cross-check that the overlap restructure changed nothing
    "dp-shard-ov4": ["-f", "dp", "-g", "2", "--batch-size", "32",
                     "--dp-shard-update", "--comm-buckets", "4"],
    "gpipe": ["-f", "gpipe", "-g", "2",
              "--micro-batch-size", "8", "--num-microbatches", "4"],
    "pipedream": ["-f", "pipedream", "-g", "2",
                  "--micro-batch-size", "8", "--num-microbatches", "4"],
    "hetero": ["-f", "gpipe", "-g", "4", "--stage-replication", "1,3",
               "--micro-batch-size", "6", "--num-microbatches", "5"],
    "hetero-pd": ["-f", "pipedream", "-g", "4", "--stage-replication", "1,3",
                  "--micro-batch-size", "6", "--num-microbatches", "5"],
    # interleaved (virtual-stage) timetables: 2 model chunks per device
    "gpipe-iv": ["-f", "gpipe", "-g", "2", "--virtual-stages", "2",
                 "--micro-batch-size", "8", "--num-microbatches", "4"],
    "pipedream-iv": ["-f", "pipedream", "-g", "2", "--virtual-stages", "2",
                     "--micro-batch-size", "8", "--num-microbatches", "4"],
}


def run_engine(name: str, data_dir: str, args) -> dict:
    argv = [sys.executable, "-m", "ddlbench_tpu.cli",
            "-b", "mnist", "-m", args.arch, "-e", str(args.epochs),
            "-p", "1000", "--dtype", "float32", "--lr", str(args.lr),
            "-s", "--data-dir", data_dir, "--platform", args.platform,
            *ENGINES[name]]
    env = dict(os.environ)
    if args.platform == "cpu":
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    try:
        r = subprocess.run(argv, capture_output=True, text=True, env=env,
                           timeout=args.timeout_s)
    except subprocess.TimeoutExpired:
        # one slow engine must not discard the others' completed results
        return {"error": f"timeout > {args.timeout_s}s"}
    result = None
    for line in r.stdout.splitlines():
        if line.startswith("result: "):
            result = json.loads(line[len("result: "):])
    if r.returncode != 0 or result is None:
        tail = (r.stderr or "").strip().splitlines()[-5:]
        return {"error": f"rc={r.returncode}", "stderr_tail": tail}
    return {
        "final_accuracy": result["valid_accuracy"],
        "accuracy_per_epoch": [h["accuracy"]
                               for h in result.get("valid_history", [])],
        "samples_per_sec": result["samples_per_sec"],
        "argv": argv[2:],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=20)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--arch", default="lenet")
    p.add_argument("--threshold", type=float, default=0.97,
                   help="minimum final validation accuracy per engine")
    p.add_argument("--max-spread", type=float, default=0.02,
                   help="maximum final-accuracy spread across engines")
    p.add_argument("--engines", default="single,dp,gpipe,pipedream,hetero")
    p.add_argument("--data-dir", default=None,
                   help="where to export/reuse the digits IDX files "
                        "(default: a temp dir)")
    p.add_argument("--timeout-s", type=int, default=1800)
    p.add_argument("--platform", default="cpu",
                   help="cpu (8-virtual-device mesh; the default) or tpu — "
                        "single-chip engines (single/dp-1) can collect a "
                        "REAL-chip accuracy point in a tunnel window")
    args = p.parse_args(argv)

    names = [e.strip() for e in args.engines.split(",") if e.strip()]
    unknown = [e for e in names if e not in ENGINES]
    if unknown:
        p.error(f"unknown engines {unknown}; choose from {sorted(ENGINES)}")

    from ddlbench_tpu.data.digits import export_digits_idx

    data_dir = args.data_dir or os.path.join(
        tempfile.gettempdir(), "ddlbench_digits")
    export_digits_idx(data_dir)

    engines = {}
    for name in names:
        print(f"accparity: training {name} ({args.epochs} epochs)...",
              file=sys.stderr, flush=True)
        engines[name] = run_engine(name, data_dir, args)

    finals = {n: e["final_accuracy"] for n, e in engines.items()
              if "final_accuracy" in e}
    spread = (max(finals.values()) - min(finals.values())) if finals else None
    ok = (len(finals) == len(names)
          and all(v >= args.threshold for v in finals.values())
          and spread is not None and spread <= args.max_spread)
    doc = {
        "dataset": "sklearn load_digits: 1797 real handwritten digit scans "
                   "(8x8 UCI optdigits), exported as 28x28 MNIST IDX; "
                   "stratified 1498 train / 299 test",
        "protocol": f"{args.epochs} epochs, SGD lr={args.lr} "
                    f"(dp scales by world), global batch 32 (hetero 30), "
                    f"per-epoch validation accuracy "
                    f"(mnist_pytorch.py:102-133 parity)",
        "arch": args.arch,
        "engines": engines,
        "final_accuracies": finals,
        "final_spread": spread,
        "threshold": args.threshold,
        "max_spread": args.max_spread,
        "pass": ok,
    }
    print(json.dumps(doc))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
