"""Hetero-conveyor A/B microbenchmark: flat-axis engine vs regular grid.

The uneven-replication engine (parallel/hetero.py) runs R ppermute rounds of
a max-interior-activation buffer per tick plus a gradient ring per sync —
wire traffic the regular 2-D ('data','stage') mesh does not pay. This tool
quantifies that overhead where the two engines are comparable: a UNIFORM
replication plan (e.g. 2,2), which both can execute at the same topology and
global batch. It also runs one genuinely uneven plan (e.g. 1,3) for the
capability-side number (no uniform-mesh comparator exists there — the
reference executes such plans via round-robin + LCM,
pipedream-fork/runtime/runtime.py:663-690).

Each point prints one JSON line:

    {"engine": "hetero"|"grid", "plan": [2,2], "samples_per_sec": N,
     "ms_per_step": N, "peak_bytes_in_use": N|null}

and a final {"comparison": ...} line with the hetero/grid throughput ratio.
Needs sum(plan) attached devices; with fewer it emits a skip record and
exits 0 (the axon tunnel exposes one real chip — the multi-chip numbers come
from the virtual CPU mesh unless a larger slice is attached).

Usage:
    python -m ddlbench_tpu.tools.heterobench [-b mnist] [-m resnet18]
        [--plan 2,2] [--uneven 1,3] [--steps 10] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys


def _peak_bytes():
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use")
    except Exception:
        return None


def _run_engine(strategy, cfg, steps, warmup):
    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.tools.timing import timed_steps

    data = make_synthetic(cfg.dataset(), cfg.global_batch(),
                          steps_per_epoch=steps)
    ts = strategy.init(jax.random.key(cfg.seed))
    lr = jnp.float32(cfg.resolved_lr())

    def run_step(x, y):
        nonlocal ts
        ts, m = strategy.train_step(ts, *strategy.shard_batch(x, y), lr)
        return m

    return timed_steps(run_step, data.batch, steps, warmup)


def _measure(engine_name, plan, cfg, strategy, steps, warmup):
    dt = _run_engine(strategy, cfg, steps, warmup)
    rec = {
        "engine": engine_name,
        "plan": list(plan),
        "samples_per_sec": round(steps * cfg.global_batch() / dt, 2),
        "ms_per_step": round(dt / steps * 1e3, 2),
        "peak_bytes_in_use": _peak_bytes(),
    }
    print(json.dumps(rec), flush=True)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-b", "--benchmark", default="mnist")
    p.add_argument("-m", "--model", default="resnet18")
    p.add_argument("-f", "--framework", default="pipedream",
                   choices=("gpipe", "pipedream"))
    p.add_argument("--plan", default="2,2",
                   help="uniform replication plan for the A/B (hetero vs grid)")
    p.add_argument("--uneven", default="1,3",
                   help="uneven plan measured hetero-only ('' to skip)")
    p.add_argument("--micro-batch-size", type=int, default=None)
    p.add_argument("--num-microbatches", type=int, default=None)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--only", default=None,
                   choices=("hetero", "grid", "uneven"),
                   help="measure one point in THIS process (used by the "
                        "subprocess-per-point default so peak_bytes_in_use "
                        "is per-engine, not a process-lifetime max)")
    p.add_argument("--in-process", action="store_true",
                   help="run all points in one process (faster; memory "
                        "figures then reflect the process max, reported as "
                        "null past the first point)")
    from ddlbench_tpu.distributed import add_platform_arg, apply_platform

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)

    import jax

    from ddlbench_tpu.config import RunConfig
    from ddlbench_tpu.distributed import enable_compilation_cache
    from ddlbench_tpu.models.zoo import get_model
    from ddlbench_tpu.parallel.api import make_strategy
    from ddlbench_tpu.parallel.hetero import (
        HeteroGPipeStrategy,
        HeteroPipeDreamStrategy,
    )

    enable_compilation_cache()
    plan = tuple(int(r) for r in args.plan.split(","))
    uneven = tuple(int(r) for r in args.uneven.split(",")) if args.uneven else ()
    need = max(sum(plan), sum(uneven) if uneven else 0)
    avail = len(jax.devices())
    if avail < need:
        print(json.dumps({
            "skipped": f"needs {need} devices, {avail} attached",
            "platform": jax.devices()[0].platform,
        }), flush=True)
        return 0

    hetero_cls = (HeteroGPipeStrategy if args.framework == "gpipe"
                  else HeteroPipeDreamStrategy)

    import math

    def base_cfg(repl):
        cfg = RunConfig(
            benchmark=args.benchmark, strategy=args.framework,
            arch=args.model, num_devices=sum(repl),
            stage_replication=tuple(repl),
            micro_batch_size=args.micro_batch_size,
            num_microbatches=args.num_microbatches,
            compute_dtype=args.dtype, steps_per_epoch=args.steps)
        if args.micro_batch_size is None:
            # replicas split each microbatch's rows: round the default
            # micro-batch down to a multiple of lcm(repl) so every plan in
            # the A/B is executable at (nearly) the same global batch
            l = math.lcm(*repl)
            mb, _ = cfg.resolved_batches()
            cfg = cfg.replace(micro_batch_size=max(l, mb // l * l))
        return cfg

    def run_point(which):
        """Measure one engine point in this process; returns its record."""
        if which == "uneven":
            cfg = base_cfg(uneven)
            cfg.validate()
            return _measure("hetero", uneven, cfg,
                            hetero_cls(get_model(cfg.arch, cfg.benchmark),
                                       cfg),
                            args.steps, args.warmup)
        cfg = base_cfg(plan)
        cfg.validate()
        if which == "hetero":
            # conveyor engine constructed directly — the strategy factory
            # rewrites uniform plans onto the grid (api.py:122-134)
            strat = hetero_cls(get_model(cfg.arch, cfg.benchmark), cfg)
        else:
            # the same topology on the regular 2-D mesh (make_strategy's pick)
            strat = make_strategy(cfg)
        return _measure(which, plan, cfg, strat, args.steps, args.warmup)

    if args.only:
        run_point(args.only)
        return 0

    points = ["hetero", "grid"] + (["uneven"] if uneven else [])
    records = {}
    if args.in_process:
        for i, which in enumerate(points):
            rec = run_point(which)
            if i > 0:
                # memory_stats peaks are a process-lifetime max: only the
                # first point's figure is attributable to its engine
                rec["peak_bytes_in_use"] = None
            records[which] = rec
    else:
        # subprocess per point: fresh process => per-engine peak memory
        import subprocess

        base_argv = [sys.executable, "-m", "ddlbench_tpu.tools.heterobench",
                     "-b", args.benchmark, "-m", args.model,
                     "-f", args.framework, "--plan", args.plan,
                     "--uneven", args.uneven or "",
                     "--steps", str(args.steps),
                     "--warmup", str(args.warmup), "--dtype", args.dtype]
        if args.micro_batch_size is not None:
            base_argv += ["--micro-batch-size", str(args.micro_batch_size)]
        if args.num_microbatches is not None:
            base_argv += ["--num-microbatches", str(args.num_microbatches)]
        if args.platform:
            base_argv += ["--platform", args.platform]
        for which in points:
            out = subprocess.run(base_argv + ["--only", which],
                                 capture_output=True, text=True)
            line = next((ln for ln in out.stdout.splitlines()
                         if ln.startswith("{")), None)
            if out.returncode or line is None:
                print(json.dumps({"engine": which, "error":
                                  (out.stderr or "no output")[-300:]}),
                      flush=True)
                continue
            records[which] = json.loads(line)
            print(line, flush=True)

    if all("samples_per_sec" in records.get(k, {}) for k in ("hetero",
                                                             "grid")):
        print(json.dumps({
            "comparison": "hetero/grid",
            "plan": list(plan),
            "throughput_ratio": round(
                records["hetero"]["samples_per_sec"]
                / records["grid"]["samples_per_sec"], 4),
            "platform": jax.devices()[0].platform,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
