"""Scaling-curve harness: strategy throughput vs chip count.

The north-star measurement (BASELINE.md): ResNet-50/ImageNet images/sec/chip
and DP-vs-pipeline scaling efficiency from 1 to N chips. This tool sweeps
strategies over growing device counts on whatever mesh exists — the real TPU
slice when one is attached, or the virtual CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=N) for harness validation —
and prints one JSON line per (strategy, n_devices) point:

    {"strategy": "dp", "devices": 4, "samples_per_sec": N,
     "per_chip": N, "efficiency": N}

``efficiency`` is per-chip throughput relative to the 1-chip single-strategy
anchor (the reference's scaling-efficiency definition; weak scaling — the
global batch grows with the chip count for dp/fsdp, stays per-pipeline for
gpipe/pipedream).

Usage:
    python -m ddlbench_tpu.tools.scalebench [-b imagenet] [-m resnet50]
        [--devices 1,2,4,8] [--strategies dp,gpipe,pipedream]
        [--steps 10] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys


def opt_state_bytes_per_chip(ts) -> int:
    """ACTUAL optimizer-state bytes resident on one chip: the summed
    addressable-shard bytes of every ``ts.opt`` leaf on device 0 —
    replicated leaves count in full, ZeRO-1-sharded leaves count their
    1/world slice, so the hybrid PP x ZeRO-1 memory win is a countable
    JSON field instead of a claim."""
    import jax

    opt = getattr(ts, "opt", None)
    if opt is None:
        return 0
    d0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree.leaves(opt):
        if not hasattr(leaf, "addressable_shards"):
            continue
        total += sum(sh.data.nbytes for sh in leaf.addressable_shards
                     if sh.device == d0)
    return int(total)


def _run_point(cfg, steps: int, warmup: int, repeats: int = 1):
    import statistics

    import jax
    import jax.numpy as jnp

    from ddlbench_tpu.data.synthetic import make_synthetic
    from ddlbench_tpu.parallel.api import make_strategy
    from ddlbench_tpu.tools.timing import timed_steps

    strategy = make_strategy(cfg)
    data = make_synthetic(cfg.dataset(), cfg.global_batch(),
                          steps_per_epoch=steps)
    ts = strategy.init(jax.random.key(cfg.seed))
    opt_bytes = opt_state_bytes_per_chip(ts)
    lr = jnp.float32(cfg.resolved_lr())

    def run_step(x, y):
        nonlocal ts
        ts, m = strategy.train_step(ts, *strategy.shard_batch(x, y), lr)
        return m

    # Median of ``repeats`` timed loops: the shared axon tunnel's throughput
    # swings +-20-45% run to run (measured round 3: the identical single-
    # strategy point read 840 then 1590 img/s minutes apart), and a scaling
    # CURVE amplifies per-point noise into fake efficiency cliffs. Warmup
    # (compile) is paid once; later loops reuse the jitted step.
    dts = [timed_steps(run_step, data.batch, steps, warmup)
           for _ in range(max(1, repeats))]
    return steps * cfg.global_batch() / statistics.median(dts), opt_bytes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-b", "--benchmark", default="imagenet")
    p.add_argument("-m", "--model", default="resnet50")
    p.add_argument("--devices", default=None,
                   help="comma list of chip counts (default: 1,2,4,... up to "
                        "the attached device count)")
    p.add_argument("--strategies", default="dp,gpipe,pipedream")
    p.add_argument("--batch-size", type=int, default=None,
                   help="per-device batch for dp; global for pipelines")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--repeats", type=int, default=1,
                   help="timed loops per point; the reported figure is the "
                        "median (3+ recommended on the shared TPU tunnel)")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--dp-shard-update", action="store_true",
                   help="run the dp points with the explicit sharded weight "
                        "update (ZeRO-1; parallel/dp.py) — A/B against a "
                        "plain run to price the reduce-scatter/all-gather "
                        "pattern")
    p.add_argument("--allreduce-dtype", default="f32",
                   choices=("f32", "float32", "bf16", "bfloat16", "int8"),
                   help="wire dtype for dp's gradient collectives "
                        "(bf16 = compressed allreduce, int8 = absmax + "
                        "stochastic rounding at quarter bytes)")
    p.add_argument("--comm-buckets", type=int, default=1,
                   help="dp points: layer-aligned gradient buckets for "
                        "comm/compute overlap (1 = monolithic)")
    from ddlbench_tpu.partition.schedule import PIPE_SCHEDULES

    p.add_argument("--pipe-schedule", default="fill-drain",
                   choices=PIPE_SCHEDULES,
                   help="gpipe points: pipeline timetable executed by the "
                        "schedule runtime (parallel/pipeline_rt.py) — the "
                        "round-10 A/B column; analytic bubble fractions "
                        "ride the JSON points for comparison against the "
                        "telemetry/bubble.py measured value")
    p.add_argument("--virtual-stages", type=int, default=1,
                   help="gpipe points: model chunks per device (fill-drain "
                        "interleaving, or the interleaved-1f1b schedule)")
    p.add_argument("--dp-replicas", type=int, default=1,
                   help="pipeline points: data replicas per stage on the "
                        "2-D pipe mesh (stages = devices/replicas). With "
                        "--dp-shard-update, gpipe points run the hybrid "
                        "PP x ZeRO-1 engine — opt_state_bytes_per_chip in "
                        "the JSON is where the memory win shows up")
    p.add_argument("--audit", default=None, metavar="PATH",
                   help="also emit the compiled-program audit manifest per "
                        "point (telemetry/audit.py: flops/HBM/per-"
                        "collective ledger + comm_stats tie-outs) into one "
                        "ledger JSON — the tools/auditbench.py diff "
                        "substrate")
    from ddlbench_tpu.distributed import (add_platform_arg, apply_comm_flags,
                                          apply_platform)

    add_platform_arg(p)
    args = p.parse_args(argv)
    apply_platform(args.platform)
    if args.comm_buckets > 1:
        apply_comm_flags(args.platform)

    import jax

    from ddlbench_tpu.config import RunConfig
    from ddlbench_tpu.distributed import enable_compilation_cache

    enable_compilation_cache()
    # Backend provenance header: one JSON line recording what jax ACTUALLY
    # selected (shared helper — distributed.record_provenance: adds
    # schema_version and fires the cpu-fallback warning), so every
    # scalebench artifact self-identifies.
    from ddlbench_tpu.distributed import record_provenance

    prov = record_provenance(args.platform, "scalebench")
    print(json.dumps({"provenance": {**prov, "platform_arg": args.platform}}),
          flush=True)
    audit_manifests = []
    avail = len(jax.devices())
    if args.devices:
        counts = [int(c) for c in args.devices.split(",")]
    else:
        counts = [c for c in (1, 2, 4, 8, 16, 32) if c <= avail]
    bad = [c for c in counts if c > avail]
    if bad:
        p.error(f"device counts {bad} exceed the {avail} attached devices")

    # 1-chip anchor: the single strategy (the reference's baseline driver)
    anchor_cfg = RunConfig(
        benchmark=args.benchmark, strategy="single", arch=args.model,
        batch_size=args.batch_size, compute_dtype=args.dtype,
        steps_per_epoch=args.steps)
    anchor, anchor_opt = _run_point(anchor_cfg, args.steps, args.warmup,
                                    args.repeats)
    print(json.dumps({"strategy": "single", "devices": 1,
                      "schema_version": prov["schema_version"],
                      "samples_per_sec": round(anchor, 2),
                      "per_chip": round(anchor, 2), "efficiency": 1.0,
                      "opt_state_bytes_per_chip": anchor_opt}),
          flush=True)

    for strat in args.strategies.split(","):
        strat = strat.strip()
        for n in counts:
            # n == 1 is a legitimate point too (1-stage pipelines measure
            # the microbatching overhead vs the single anchor)
            kw = dict(benchmark=args.benchmark, strategy=strat,
                      arch=args.model, num_devices=n,
                      compute_dtype=args.dtype, steps_per_epoch=args.steps,
                      batch_size=args.batch_size)
            if strat not in ("dp", "fsdp"):
                kw["num_stages"] = n
            point = {"strategy": strat, "devices": n}
            if strat in ("gpipe", "pipedream") and args.dp_replicas > 1:
                if n % args.dp_replicas:
                    print(json.dumps({**point, "error":
                                      f"{n} devices not divisible by "
                                      f"--dp-replicas {args.dp_replicas}"}),
                          flush=True)
                    continue
                kw["num_stages"] = n // args.dp_replicas
                kw["dp_replicas"] = args.dp_replicas
                point["dp_replicas"] = args.dp_replicas
            if strat == "gpipe" and (args.pipe_schedule != "fill-drain"
                                     or args.virtual_stages > 1):
                kw["pipe_schedule"] = args.pipe_schedule
                kw["virtual_stages"] = args.virtual_stages
                point["pipe_schedule"] = args.pipe_schedule
                point["virtual_stages"] = args.virtual_stages
            if strat == "gpipe":
                # hybrid PP x ZeRO-1 on/off is an A/B column: the flag
                # rides every gpipe point so the JSON rows pair up
                kw["dp_shard_update"] = args.dp_shard_update
                kw["comm_buckets"] = (args.comm_buckets
                                      if args.dp_shard_update else 1)
                point["dp_shard_update"] = args.dp_shard_update
                if args.dp_shard_update:
                    point["comm_buckets"] = kw["comm_buckets"]
            if strat == "dp" and (args.dp_shard_update
                                  or args.comm_buckets > 1
                                  or args.allreduce_dtype not in
                                  ("f32", "float32")):
                kw["dp_shard_update"] = args.dp_shard_update
                kw["allreduce_dtype"] = args.allreduce_dtype
                kw["comm_buckets"] = args.comm_buckets if n > 1 else 1
                point["dp_shard_update"] = args.dp_shard_update
                point["allreduce_dtype"] = args.allreduce_dtype
                point["comm_buckets"] = kw["comm_buckets"]
            cfg = RunConfig(**kw)
            try:
                cfg.validate()
                if "pipe_schedule" in point:
                    # analytic bubble rides the point for the round-10
                    # report table; inside the try so an infeasible
                    # (schedule, S, M) point records its error like any
                    # other instead of killing the sweep
                    from ddlbench_tpu.partition.schedule import (
                        bubble_is_estimate, schedule_bubble_fraction)

                    _, chunks_b = cfg.resolved_batches()
                    point["bubble_analytic"] = round(
                        schedule_bubble_fraction(
                            args.pipe_schedule, cfg.resolved_stages(),
                            chunks_b, args.virtual_stages), 4)
                    if bubble_is_estimate(args.pipe_schedule,
                                          cfg.resolved_stages(), chunks_b,
                                          args.virtual_stages):
                        point["bubble_analytic_is_lower_bound"] = True
                ips, opt_bytes = _run_point(cfg, args.steps, args.warmup,
                                            args.repeats)
                if args.audit:
                    from ddlbench_tpu.telemetry.audit import \
                        audit_train_config

                    man, _ = audit_train_config(
                        cfg, name=f"scale/{strat}@{n}")
                    audit_manifests.append(man)
                    point["audit_tie_ok"] = man["reconcile"].get("ok")
                    point["audit_tieable"] = man["reconcile"]["tieable"]
            except Exception as e:  # point failures shouldn't kill the sweep
                print(json.dumps({**point, "error": str(e)[:200]}),
                      flush=True)
                continue
            print(json.dumps({
                **point,
                "schema_version": prov["schema_version"],
                "samples_per_sec": round(ips, 2),
                "per_chip": round(ips / n, 2),
                "efficiency": round(ips / n / anchor, 4),
                "opt_state_bytes_per_chip": opt_bytes,
            }), flush=True)
    if args.audit:
        from ddlbench_tpu.telemetry.audit import write_manifests

        write_manifests(args.audit, audit_manifests,
                        header={**prov, "tool": "scalebench"})
        print(json.dumps({"audit": args.audit,
                          "programs": len(audit_manifests)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
