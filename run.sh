#!/usr/bin/env bash
# Benchmark harness CLI — mirrors the reference's run/run/run.sh flag surface
# (-b benchmark -f framework -g devices -m model -p loginterval -s real-data;
# reference run.sh:16-47) but dispatches to the in-process Python CLI instead
# of generating SLURM jobs: on TPU one process drives the whole mesh, so the
# sbatch/ssh/mpirun layer (run_template.sh) has no equivalent.
#
# Examples:
#   ./run.sh -b mnist -f single -m resnet18
#   ./run.sh -b cifar10 -f dp -g 8 -m resnet50
#   ./run.sh -b imagenet -f gpipe -g 4 -m vgg16
set -euo pipefail
exec python -m ddlbench_tpu.cli "$@"
